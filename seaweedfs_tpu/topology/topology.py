"""Topology root: collections, volume layouts, EC shard registry, vid/fid
assignment (ref: weed/topology/topology.go, topology_ec.go)."""

from __future__ import annotations

import secrets as _secrets
import threading
from typing import Dict, Optional

from ..sequence import MemorySequencer
from ..storage.erasure_coding import TOTAL_SHARDS_COUNT
from ..storage.erasure_coding.ec_volume import ShardBits
from ..storage.file_id import format_needle_id_cookie
from ..storage.super_block import ReplicaPlacement
from ..storage.ttl import EMPTY_TTL, TTL
from .node import DataCenter, DataNode, Node
from .volume_layout import VolumeLayout


class Collection:
    def __init__(self, name: str, volume_size_limit: int):
        self.name = name
        self.volume_size_limit = volume_size_limit
        self._layouts: Dict[tuple[int, int], VolumeLayout] = {}
        self._lock = threading.RLock()

    def get_or_create_layout(
        self, rp: ReplicaPlacement, ttl: TTL
    ) -> VolumeLayout:
        key = (rp.to_byte(), ttl.to_u32())
        with self._lock:
            layout = self._layouts.get(key)
            if layout is None:
                layout = VolumeLayout(rp, ttl, self.volume_size_limit)
                self._layouts[key] = layout
            return layout

    def layouts(self) -> list[VolumeLayout]:
        with self._lock:
            return list(self._layouts.values())

    def lookup(self, vid: int) -> Optional[list[DataNode]]:
        for layout in self.layouts():
            locs = layout.lookup(vid)
            if locs:
                return locs
        return None


class EcShardLocations:
    """vid -> per-shard DataNode lists (ref: topology_ec.go:10-124)."""

    def __init__(self, collection: str = ""):
        self.collection = collection
        # 32 slots (the ShardBits width) so alternate geometries with more
        # than 14 shards (e.g. 12.4) register cleanly
        self.locations: list[list[DataNode]] = [[] for _ in range(32)]
        # highest shard id ever registered + 1: the repair scheduler's
        # expectation of how many shards this volume SHOULD have, so a
        # shard whose every holder died still counts as missing
        self.expected_total = 0

    def add_shard(self, shard_id: int, dn: DataNode) -> bool:
        if shard_id + 1 > self.expected_total:
            self.expected_total = shard_id + 1
        if dn in self.locations[shard_id]:
            return False
        self.locations[shard_id].append(dn)
        return True

    def delete_shard(self, shard_id: int, dn: DataNode) -> bool:
        if dn in self.locations[shard_id]:
            self.locations[shard_id].remove(dn)
            return True
        return False


class Topology(Node):
    def __init__(
        self,
        volume_size_limit: int = 30_000 * 1024 * 1024,
        sequencer: Optional[MemorySequencer] = None,
    ):
        super().__init__("topo")
        self.volume_size_limit = volume_size_limit
        self.sequence = sequencer or MemorySequencer()
        self.collections: Dict[str, Collection] = {}
        self.ec_shard_map: Dict[tuple[str, int], EcShardLocations] = {}
        self._ec_lock = threading.RLock()
        self._vid_lock = threading.Lock()
        self._max_volume_id_assigned = 0

    # --- tree ---
    def get_or_create_data_center(self, dc_id: str) -> DataCenter:
        with self._lock:
            dc = self.children.get(dc_id)
            if isinstance(dc, DataCenter):
                return dc
            dc = DataCenter(dc_id)
            self.link_child(dc)
            return dc

    # --- id assignment ---
    def next_volume_id(self) -> int:
        """Monotonic cluster-wide volume id (raft-backed in the reference,
        ref topology.go:115-122; single-master lease here)."""
        with self._vid_lock:
            vid = max(self.max_volume_id, self._max_volume_id_assigned) + 1
            self._max_volume_id_assigned = vid
            return vid

    def pick_for_write(
        self, count: int, collection: str, rp: ReplicaPlacement, ttl: TTL
    ) -> tuple[str, int, list[DataNode]]:
        """-> (fid, count, locations) (ref topology.go:129-139)."""
        layout = self.get_volume_layout(collection, rp, ttl)
        vid, locations = layout.pick_for_write()
        file_id = self.sequence.next_file_id(count)
        cookie = _secrets.randbits(32)
        fid = f"{vid},{format_needle_id_cookie(file_id, cookie)}"
        return fid, count, locations

    # --- collections / layouts ---
    def get_collection(self, name: str) -> Collection:
        with self._lock:
            col = self.collections.get(name)
            if col is None:
                col = Collection(name, self.volume_size_limit)
                self.collections[name] = col
            return col

    def get_volume_layout(
        self, collection: str, rp: ReplicaPlacement, ttl: TTL
    ) -> VolumeLayout:
        return self.get_collection(collection).get_or_create_layout(rp, ttl)

    def delete_collection(self, name: str) -> None:
        with self._lock:
            self.collections.pop(name, None)

    # --- volume registration from heartbeats ---
    def _layout_for_info(self, info: dict) -> VolumeLayout:
        rp = ReplicaPlacement.from_byte(int(info.get("replica_placement", 0)))
        ttl = TTL.from_u32(int(info.get("ttl", 0)))
        return self.get_volume_layout(info.get("collection", ""), rp, ttl)

    def register_volume(self, info: dict, dn: DataNode) -> None:
        self._layout_for_info(info).register_volume(info, dn)
        self.adjust_max_volume_id(int(info["id"]))

    def unregister_volume(self, info: dict, dn: DataNode) -> None:
        self._layout_for_info(info).unregister_volume(info, dn)

    def lookup(self, collection: str, vid: int) -> Optional[list[DataNode]]:
        """(ref topology.go:91-108)"""
        if collection:
            col = self.collections.get(collection)
            return col.lookup(vid) if col else None
        for col in list(self.collections.values()):
            locs = col.lookup(vid)
            if locs:
                return locs
        return None

    # --- EC shards (ref topology_ec.go) ---
    def register_ec_shards(
        self, vid: int, collection: str, bits: ShardBits, dn: DataNode
    ) -> None:
        with self._ec_lock:
            key = (collection, vid)
            locs = self.ec_shard_map.get(key)
            if locs is None:
                locs = EcShardLocations(collection)
                self.ec_shard_map[key] = locs
            for shard_id in bits.shard_ids():
                locs.add_shard(shard_id, dn)

    def unregister_ec_shards(
        self, vid: int, collection: str, bits: ShardBits, dn: DataNode
    ) -> None:
        with self._ec_lock:
            locs = self.ec_shard_map.get((collection, vid))
            if locs is None:
                return
            for shard_id in bits.shard_ids():
                locs.delete_shard(shard_id, dn)

    def forget_ec_volume_if_empty(self, vid: int) -> bool:
        """Drop an EC volume's registration once EXPLICIT shard deletes
        (ec.decode, lifecycle re-inflation) emptied every location list.
        Only delta/full-state delete processing calls this — a node going
        silent must NOT forget the volume, or wholly-lost shards would
        stop looking missing to the repair planner."""
        with self._ec_lock:
            for (collection, v), locs in list(self.ec_shard_map.items()):
                if v == vid and not any(
                    locs.locations[s] for s in range(32)
                ):
                    del self.ec_shard_map[(collection, v)]
                    return True
        return False

    def lookup_ec_shards(self, vid: int) -> Optional[EcShardLocations]:
        with self._ec_lock:
            for (collection, v), locs in self.ec_shard_map.items():
                if v == vid:
                    return locs
            return None

    def data_nodes(self) -> list[DataNode]:
        return list(self.descend_data_nodes())

    # --- anti-entropy state snapshots (consumed by topology/repair.py) ---
    def live_data_nodes(self, grace_seconds: float) -> list[DataNode]:
        """Nodes whose heartbeats are fresh. A node silent past the grace
        period stops counting as a holder — the heartbeat-driven failure
        detector feeding the repair scheduler (a broken stream already
        unregisters the node; this also catches a hung one that keeps the
        stream open without pulsing)."""
        import time as _time

        now = _time.time()
        return [
            dn
            for dn in self.data_nodes()
            if now - dn.last_seen <= grace_seconds
        ]

    def ec_states(self, live_urls: Optional[set] = None) -> list[dict]:
        """Per-EC-volume holder map restricted to live nodes, in the shape
        `repair.plan_ec_repairs` consumes."""
        out = []
        with self._ec_lock:
            for (collection, vid), locs in self.ec_shard_map.items():
                if locs.expected_total == 0:
                    continue
                holders: Dict[int, list[str]] = {}
                for sid in range(locs.expected_total):
                    urls = [
                        dn.url
                        for dn in locs.locations[sid]
                        if live_urls is None or dn.url in live_urls
                    ]
                    if urls:
                        holders[sid] = urls
                out.append(
                    {
                        "vid": vid,
                        "collection": collection,
                        "total_shards": locs.expected_total,
                        "holders": holders,
                    }
                )
        return out

    def replica_states(self, live_urls: Optional[set] = None) -> dict:
        """{vid: [per-live-replica digest/frontier/corrupt records]} for
        `repair.plan_replica_repairs`, read straight off the volume infos
        heartbeats delivered."""
        states: Dict[int, list[dict]] = {}
        for dn in self.data_nodes():
            if live_urls is not None and dn.url not in live_urls:
                continue
            for vid, info in list(dn.volumes.items()):
                states.setdefault(int(vid), []).append(
                    {
                        "url": dn.url,
                        "collection": info.get("collection", ""),
                        "content_digest": int(info.get("content_digest", 0)),
                        "append_at_ns": int(info.get("append_at_ns", 0)),
                        "scrub_corrupt": bool(info.get("scrub_corrupt")),
                        "read_only": bool(info.get("read_only")),
                        "garbage_ratio": float(info.get("garbage_ratio", 0.0)),
                        # lifecycle fields (ride full messages + the slim
                        # digest refresh, like garbage_ratio)
                        "read_heat": float(info.get("read_heat", 0.0)),
                        "write_heat": float(info.get("write_heat", 0.0)),
                        "size": int(info.get("size", 0)),
                    }
                )
        return states

    def placement_states(self, live_urls: Optional[set] = None) -> list[dict]:
        """Per-volume replica placement snapshot — each volume's layout
        `ReplicaPlacement` plus its live holders' (dc, rack) domains, in
        the shape `placement.plan_replica_spread` consumes."""
        out = []
        with self._lock:
            collections = list(self.collections.items())
        for cname, col in collections:
            for layout in col.layouts():
                rp_byte = layout.replica_placement.to_byte()
                with layout._lock:
                    vid_locs = {
                        vid: list(locs)
                        for vid, locs in layout.vid_to_locations.items()
                    }
                for vid, locs in vid_locs.items():
                    holders = [
                        {
                            "url": dn.url,
                            "dc": dn.data_center.id if dn.data_center else "",
                            "rack": dn.rack.id if dn.rack else "",
                        }
                        for dn in locs
                        if live_urls is None or dn.url in live_urls
                    ]
                    if holders:
                        out.append(
                            {
                                "vid": int(vid),
                                "collection": cname,
                                "replica_placement": rp_byte,
                                "holders": holders,
                            }
                        )
        return out

    def placement_candidates(
        self, live_urls: Optional[set] = None
    ) -> list[dict]:
        """Every live node with its failure domains and free slots — the
        move-target pool for placement repair planning."""
        return [
            {
                "url": dn.url,
                "dc": dn.data_center.id if dn.data_center else "",
                "rack": dn.rack.id if dn.rack else "",
                "free": dn.free_space(),
            }
            for dn in self.data_nodes()
            if live_urls is None or dn.url in live_urls
        ]

    def ec_heat_states(self, live_urls: Optional[set] = None) -> dict:
        """{vid: {collection, read_heat, local_bits, offloaded_bits}}
        with heat SUMMED (and tier bits OR-ed) across live shard holders —
        the input of `lifecycle.plan_reinflations` / `plan_offloads` /
        `plan_recalls`. Heat and the cold-tier split per holder come from
        the per-pulse EC heat refresh the master stores on each DataNode
        (`dn.ec_heat` / `dn.ec_tier`)."""
        out: Dict[int, dict] = {}
        with self._ec_lock:
            registered = {
                vid: collection
                for (collection, vid), locs in self.ec_shard_map.items()
                if locs.expected_total
            }
        for dn in self.data_nodes():
            if live_urls is not None and dn.url not in live_urls:
                continue
            tier = getattr(dn, "ec_tier", {})
            for vid, heat in list(getattr(dn, "ec_heat", {}).items()):
                if vid not in registered or vid not in dn.ec_shards:
                    continue
                st = out.setdefault(
                    int(vid),
                    {
                        "collection": registered[vid],
                        "read_heat": 0.0,
                        "local_bits": 0,
                        "offloaded_bits": 0,
                    },
                )
                st["read_heat"] += float(heat)
                local, offloaded = tier.get(
                    vid, (dn.ec_shards.get(vid, ShardBits()).bits, 0)
                )
                st["local_bits"] |= int(local)
                st["offloaded_bits"] |= int(offloaded)
        return out

    def to_info(self) -> dict:
        return {
            "max_volume_id": self.max_volume_id,
            "volume_count": self.volume_count,
            "max_volume_count": self.max_volume_count,
            "ec_shard_count": self.ec_shard_count,
            "data_centers": [
                {
                    "id": dc.id,
                    "racks": [
                        {
                            "id": rack.id,
                            "data_nodes": [
                                dn.to_info() for dn in rack.children.values()
                            ],
                        }
                        for rack in dc.children.values()
                    ],
                }
                for dc in self.children.values()
            ],
        }
