"""Master-side lifecycle planning: the decision half of the hot→warm
lifecycle plane (pure and unit-testable, like `topology/vacuum_plan.py`;
dispatch lives in `server/master.py`).

Heartbeats are the sensor: every volume message (and the slim digest
refresh) carries the replica's decayed read/write heat plus its size,
and the per-pulse EC heat refresh carries each EC volume's read heat.
Two planners close the Haystack→f4 arc (PAPER.md) inside one cluster:

- `plan_ec_conversions` qualifies volumes that are COLD (total decayed
  heat under the cold threshold on every replica), FULL (size past
  `full_fraction` of the limit, or already sealed read-only) and HEALTHY
  (never quarantined) for auto-EC through the existing encode pipeline —
  coldest first, so the volume wasting the most hot-tier bytes for the
  least traffic converts first.
- `plan_reinflations` qualifies EC volumes whose aggregated read heat
  rose past the HOT threshold for decode back into a normal volume —
  hottest first (offloaded volumes are excluded: their shards must
  recall to local disk first, which `plan_recalls` handles at a lower
  threshold — by the time a volume is hot enough to re-inflate it is
  already local again).

The cold tier (ISSUE 14) extends the arc one band further down:

- `plan_offloads` qualifies EC volumes whose aggregated read heat fell
  below `offload_read_heat` (a band BELOW cold) for shard-file offload
  onto the configured remote backend — coldest first, and only when a
  `cold_backend` is configured.
- `plan_recalls` qualifies offloaded volumes whose heat rose past
  `recall_read_heat` for recall to local disk — hottest first.

Hysteresis lives in the threshold pairs: `hot_read_heat` must sit well
above `cold_read_heat`, and `recall_read_heat` well above
`offload_read_heat` (both enforced at config construction), so an access
mix oscillating inside a band never flaps EC↔un-EC or offload↔recall —
a volume must genuinely cool below the lower edge to descend a tier and
genuinely heat past the upper edge to climb back, and the dispatcher's
authoritative `VolumeLifecycleCheck` re-check catches anything that
changed since the heartbeat sample.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

from .repair import RepairTask

# priority is an ascending sort key in the shared RepairQueue.
# auto-EC: coldest-first  -> priority grows with heat.
# re-inflate: hottest-first -> priority shrinks (negative) with heat.
_HEAT_SCALE = 1000


def coldness_priority(total_heat: float) -> int:
    return int(round(max(total_heat, 0.0) * _HEAT_SCALE))


def hotness_priority(total_heat: float) -> int:
    return -int(round(max(total_heat, 0.0) * _HEAT_SCALE))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass(frozen=True)
class LifecycleConfig:
    """Thresholds of the lifecycle planner. Heat values are decayed op
    counts (see storage/heat.py): with the default 600s half-life,
    `cold_read_heat=0.5` roughly means "less than one read in the last
    ten minutes"."""

    cold_read_heat: float = 0.5
    cold_write_heat: float = 0.5
    hot_read_heat: float = 50.0
    full_fraction: float = 0.85
    # cold tier (ISSUE 14): a band BELOW cold — sealed EC shards of
    # volumes this cold move to the remote backend; sustained heat past
    # recall brings them back. Disabled until a backend is named
    # (SEAWEEDFS_TPU_COLD_BACKEND, e.g. "s3.cold" / "local.default").
    offload_read_heat: float = 0.05
    recall_read_heat: float = 5.0
    cold_backend: str = ""
    # anti-flap holddown: a volume the plane just RECALLED is exempt
    # from offload planning for this long, however cold it looks — the
    # heat thresholds alone are hysteresis in VALUE, this is hysteresis
    # in TIME (a short heat half-life would otherwise let a recalled
    # volume's heat collapse across the whole band between two scans
    # and ping-pong transfer bytes through the backend)
    offload_holddown_s: float = 600.0
    # optional scope: comma-separated collection names the lifecycle
    # plane may touch ("" = every collection). Operators pin archival
    # collections into the arc without exposing latency-sensitive ones
    # to conversion churn; benches scope the plane to their cold corpus.
    collections: str = ""

    def __post_init__(self):
        if self.hot_read_heat <= self.cold_read_heat:
            raise ValueError(
                "lifecycle hysteresis violated: hot_read_heat "
                f"({self.hot_read_heat}) must exceed cold_read_heat "
                f"({self.cold_read_heat})"
            )
        if self.recall_read_heat <= self.offload_read_heat:
            raise ValueError(
                "cold-tier hysteresis violated: recall_read_heat "
                f"({self.recall_read_heat}) must exceed offload_read_heat "
                f"({self.offload_read_heat})"
            )

    @classmethod
    def from_env(cls) -> "LifecycleConfig":
        return cls(
            cold_read_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_COLD_HEAT", cls.cold_read_heat
            ),
            cold_write_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_COLD_WRITE_HEAT",
                cls.cold_write_heat,
            ),
            hot_read_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_HOT_HEAT", cls.hot_read_heat
            ),
            full_fraction=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_FULL_FRACTION", cls.full_fraction
            ),
            offload_read_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_OFFLOAD_HEAT",
                cls.offload_read_heat,
            ),
            recall_read_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_RECALL_HEAT",
                cls.recall_read_heat,
            ),
            cold_backend=os.environ.get(
                "SEAWEEDFS_TPU_COLD_BACKEND", ""
            ).strip(),
            offload_holddown_s=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_OFFLOAD_HOLDDOWN",
                cls.offload_holddown_s,
            ),
            collections=os.environ.get(
                "SEAWEEDFS_TPU_LIFECYCLE_COLLECTIONS", ""
            ).strip(),
        )

    def collection_allowed(self, collection: str) -> bool:
        if not self.collections:
            return True
        return collection in {
            c.strip() for c in self.collections.split(",")
        }


def volume_total_heat(replicas: list[dict]) -> tuple[float, float]:
    """(read, write) heat summed across replicas — each replica serves a
    share of the traffic (round-robin fan-out), so the volume's true
    temperature is the sum of what every copy observed."""
    return (
        sum(float(r.get("read_heat", 0.0)) for r in replicas),
        sum(float(r.get("write_heat", 0.0)) for r in replicas),
    )


def plan_ec_conversions(
    volume_states: dict,
    volume_size_limit: int,
    cfg: LifecycleConfig,
    include_all: bool = False,
) -> list[RepairTask]:
    """Auto-EC planning over heartbeat-derived state.

    volume_states: {vid: [{url, collection, read_heat, write_heat, size,
    read_only, scrub_corrupt}, ...]} — one entry per live replica holder
    (the shape `Topology.replica_states` returns, lifecycle fields
    included).

    One task per qualifying volume, kind="lifecycle_ec", coldest first.
    Gates:
    - HEALTHY: no replica quarantined (`scrub_corrupt`) — a damaged copy
      belongs to the repair plane; converting it would bake the damage
      into the warm tier. Never waived, even by include_all.
    - COLD: summed read AND write heat under the cold thresholds.
    - FULL: the largest replica past full_fraction * volume_size_limit,
      or every replica sealed read-only (an operator-sealed volume is
      done growing regardless of size).
    include_all waives the cold/full gates (forced sweeps); the
    dispatcher's authoritative VolumeLifecycleCheck still applies them.
    """
    tasks = []
    for vid, replicas in volume_states.items():
        if not replicas:
            continue
        if not cfg.collection_allowed(replicas[0].get("collection", "")):
            continue
        if any(r.get("scrub_corrupt") for r in replicas):
            continue
        read_heat, write_heat = volume_total_heat(replicas)
        if not include_all:
            if read_heat > cfg.cold_read_heat:
                continue
            if write_heat > cfg.cold_write_heat:
                continue
            size = max(int(r.get("size", 0)) for r in replicas)
            sealed = all(r.get("read_only") for r in replicas)
            if (
                not sealed
                and volume_size_limit > 0
                and size < cfg.full_fraction * volume_size_limit
            ):
                continue
        tasks.append(
            RepairTask(
                kind="lifecycle_ec",
                vid=int(vid),
                collection=replicas[0].get("collection", ""),
                priority=coldness_priority(read_heat + write_heat),
                survivors=len(replicas),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks


def plan_reinflations(
    ec_heat_states: dict, cfg: LifecycleConfig
) -> list[RepairTask]:
    """Re-inflation planning over the per-pulse EC heat refresh.

    ec_heat_states: {vid: {"collection": str, "read_heat": float}} with
    read_heat already summed across live shard holders (the shape
    `Topology.ec_heat_states` returns). An EC volume past the HOT
    threshold becomes one kind="lifecycle_inflate" task, hottest first.
    """
    tasks = []
    for vid, st in ec_heat_states.items():
        if not cfg.collection_allowed(st.get("collection", "")):
            continue
        heat = float(st.get("read_heat", 0.0))
        if heat < cfg.hot_read_heat:
            continue
        if int(st.get("offloaded_bits", 0)):
            # shards on the remote tier: decode needs them local, and the
            # recall planner already fired at a LOWER threshold — inflate
            # re-qualifies on the scan after the recall lands
            continue
        tasks.append(
            RepairTask(
                kind="lifecycle_inflate",
                vid=int(vid),
                collection=st.get("collection", ""),
                priority=hotness_priority(heat),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks


def _bit_count(bits: int) -> int:
    from ..storage.erasure_coding.ec_volume import ShardBits

    return ShardBits(int(bits)).count()


def plan_offloads(
    ec_heat_states: dict,
    cfg: LifecycleConfig,
    recalled_at: Optional[dict] = None,
    now: float = 0.0,
) -> list[RepairTask]:
    """Cold-tier offload planning over the per-pulse EC heat refresh.

    An EC volume whose summed read heat sits below `offload_read_heat`
    and that still has LOCAL shard files becomes one
    kind="lifecycle_offload" task, coldest first — but only when the
    config names a `cold_backend` (no backend, no cold tier). Volumes
    inside the recall holddown window (`recalled_at`: {vid: monotonic
    recall-completion time}) are exempt, however cold: a transfer the
    plane just paid for in the hot direction must not immediately
    reverse. The dispatcher's authoritative VolumeLifecycleCheck
    re-applies the heat gate per holder before any transfer I/O is
    spent.
    """
    if not cfg.cold_backend:
        return []
    recalled_at = recalled_at or {}
    tasks = []
    for vid, st in ec_heat_states.items():
        if not cfg.collection_allowed(st.get("collection", "")):
            continue
        heat = float(st.get("read_heat", 0.0))
        if heat > cfg.offload_read_heat:
            continue
        if not int(st.get("local_bits", 0)):
            continue  # nothing left to offload
        t_rec = recalled_at.get(int(vid))
        if t_rec is not None and now - t_rec < cfg.offload_holddown_s:
            continue  # anti-flap: just recalled, hold it local
        tasks.append(
            RepairTask(
                kind="lifecycle_offload",
                vid=int(vid),
                collection=st.get("collection", ""),
                priority=coldness_priority(heat),
                survivors=_bit_count(st.get("local_bits", 0)),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks


def plan_recalls(
    ec_heat_states: dict, cfg: LifecycleConfig
) -> list[RepairTask]:
    """Cold-tier recall planning: an offloaded EC volume whose summed
    read heat rose past `recall_read_heat` becomes one
    kind="lifecycle_recall" task, hottest first. Recall fires well below
    the re-inflation threshold (enforced hysteresis), so a warming
    volume lands back on local disk before it could qualify to decode.
    """
    tasks = []
    for vid, st in ec_heat_states.items():
        if not cfg.collection_allowed(st.get("collection", "")):
            continue
        heat = float(st.get("read_heat", 0.0))
        if heat < cfg.recall_read_heat:
            continue
        if not int(st.get("offloaded_bits", 0)):
            continue
        tasks.append(
            RepairTask(
                kind="lifecycle_recall",
                vid=int(vid),
                collection=st.get("collection", ""),
                priority=hotness_priority(heat),
                survivors=_bit_count(st.get("offloaded_bits", 0)),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks
