"""Master-side lifecycle planning: the decision half of the hot→warm
lifecycle plane (pure and unit-testable, like `topology/vacuum_plan.py`;
dispatch lives in `server/master.py`).

Heartbeats are the sensor: every volume message (and the slim digest
refresh) carries the replica's decayed read/write heat plus its size,
and the per-pulse EC heat refresh carries each EC volume's read heat.
Two planners close the Haystack→f4 arc (PAPER.md) inside one cluster:

- `plan_ec_conversions` qualifies volumes that are COLD (total decayed
  heat under the cold threshold on every replica), FULL (size past
  `full_fraction` of the limit, or already sealed read-only) and HEALTHY
  (never quarantined) for auto-EC through the existing encode pipeline —
  coldest first, so the volume wasting the most hot-tier bytes for the
  least traffic converts first.
- `plan_reinflations` qualifies EC volumes whose aggregated read heat
  rose past the HOT threshold for decode back into a normal volume —
  hottest first.

Hysteresis lives in the threshold pair: `hot_read_heat` must sit well
above `cold_read_heat` (enforced at config construction), so an access
mix oscillating between the two never flaps EC↔un-EC — a volume must
genuinely cool below cold to leave the hot tier and genuinely heat past
hot to come back, and the dispatcher's authoritative
`VolumeLifecycleCheck` re-check catches anything that changed since the
heartbeat sample.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from .repair import RepairTask

# priority is an ascending sort key in the shared RepairQueue.
# auto-EC: coldest-first  -> priority grows with heat.
# re-inflate: hottest-first -> priority shrinks (negative) with heat.
_HEAT_SCALE = 1000


def coldness_priority(total_heat: float) -> int:
    return int(round(max(total_heat, 0.0) * _HEAT_SCALE))


def hotness_priority(total_heat: float) -> int:
    return -int(round(max(total_heat, 0.0) * _HEAT_SCALE))


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


@dataclass(frozen=True)
class LifecycleConfig:
    """Thresholds of the lifecycle planner. Heat values are decayed op
    counts (see storage/heat.py): with the default 600s half-life,
    `cold_read_heat=0.5` roughly means "less than one read in the last
    ten minutes"."""

    cold_read_heat: float = 0.5
    cold_write_heat: float = 0.5
    hot_read_heat: float = 50.0
    full_fraction: float = 0.85

    def __post_init__(self):
        if self.hot_read_heat <= self.cold_read_heat:
            raise ValueError(
                "lifecycle hysteresis violated: hot_read_heat "
                f"({self.hot_read_heat}) must exceed cold_read_heat "
                f"({self.cold_read_heat})"
            )

    @classmethod
    def from_env(cls) -> "LifecycleConfig":
        return cls(
            cold_read_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_COLD_HEAT", cls.cold_read_heat
            ),
            cold_write_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_COLD_WRITE_HEAT",
                cls.cold_write_heat,
            ),
            hot_read_heat=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_HOT_HEAT", cls.hot_read_heat
            ),
            full_fraction=_env_float(
                "SEAWEEDFS_TPU_LIFECYCLE_FULL_FRACTION", cls.full_fraction
            ),
        )


def volume_total_heat(replicas: list[dict]) -> tuple[float, float]:
    """(read, write) heat summed across replicas — each replica serves a
    share of the traffic (round-robin fan-out), so the volume's true
    temperature is the sum of what every copy observed."""
    return (
        sum(float(r.get("read_heat", 0.0)) for r in replicas),
        sum(float(r.get("write_heat", 0.0)) for r in replicas),
    )


def plan_ec_conversions(
    volume_states: dict,
    volume_size_limit: int,
    cfg: LifecycleConfig,
    include_all: bool = False,
) -> list[RepairTask]:
    """Auto-EC planning over heartbeat-derived state.

    volume_states: {vid: [{url, collection, read_heat, write_heat, size,
    read_only, scrub_corrupt}, ...]} — one entry per live replica holder
    (the shape `Topology.replica_states` returns, lifecycle fields
    included).

    One task per qualifying volume, kind="lifecycle_ec", coldest first.
    Gates:
    - HEALTHY: no replica quarantined (`scrub_corrupt`) — a damaged copy
      belongs to the repair plane; converting it would bake the damage
      into the warm tier. Never waived, even by include_all.
    - COLD: summed read AND write heat under the cold thresholds.
    - FULL: the largest replica past full_fraction * volume_size_limit,
      or every replica sealed read-only (an operator-sealed volume is
      done growing regardless of size).
    include_all waives the cold/full gates (forced sweeps); the
    dispatcher's authoritative VolumeLifecycleCheck still applies them.
    """
    tasks = []
    for vid, replicas in volume_states.items():
        if not replicas:
            continue
        if any(r.get("scrub_corrupt") for r in replicas):
            continue
        read_heat, write_heat = volume_total_heat(replicas)
        if not include_all:
            if read_heat > cfg.cold_read_heat:
                continue
            if write_heat > cfg.cold_write_heat:
                continue
            size = max(int(r.get("size", 0)) for r in replicas)
            sealed = all(r.get("read_only") for r in replicas)
            if (
                not sealed
                and volume_size_limit > 0
                and size < cfg.full_fraction * volume_size_limit
            ):
                continue
        tasks.append(
            RepairTask(
                kind="lifecycle_ec",
                vid=int(vid),
                collection=replicas[0].get("collection", ""),
                priority=coldness_priority(read_heat + write_heat),
                survivors=len(replicas),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks


def plan_reinflations(
    ec_heat_states: dict, cfg: LifecycleConfig
) -> list[RepairTask]:
    """Re-inflation planning over the per-pulse EC heat refresh.

    ec_heat_states: {vid: {"collection": str, "read_heat": float}} with
    read_heat already summed across live shard holders (the shape
    `Topology.ec_heat_states` returns). An EC volume past the HOT
    threshold becomes one kind="lifecycle_inflate" task, hottest first.
    """
    tasks = []
    for vid, st in ec_heat_states.items():
        heat = float(st.get("read_heat", 0.0))
        if heat < cfg.hot_read_heat:
            continue
        tasks.append(
            RepairTask(
                kind="lifecycle_inflate",
                vid=int(vid),
                collection=st.get("collection", ""),
                priority=hotness_priority(heat),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks
