"""Master-side vacuum planning: the decision half of the vacuum plane
(pure and unit-testable, like `topology/repair.py`; dispatch lives in
`server/master.py`).

Heartbeats are the sensor here too: every volume message (and the slim
per-few-ticks digest refresh) carries the replica's live garbage ratio, so
the scheduler ranks candidates without sweeping the cluster with RPCs.
A volume qualifies when EVERY live replica reports at least the threshold
— compaction must run on all replicas to commit, and the RPC driver
re-checks each one authoritatively (`VacuumVolumeCheck`) before spending
I/O, so a stale heartbeat ratio costs one cheap probe, never a wasted
compaction. The queue drains highest-garbage-first: the volume wasting
the most bytes is reclaimed first.
"""

from __future__ import annotations

from .repair import RepairTask

# priority is an ascending sort key (fewest-first in the shared queue);
# garbage ratio in [0,1] maps to [1000..0] so MORE garbage sorts FIRST
_PRIORITY_SCALE = 1000


def garbage_priority(ratio: float) -> int:
    return int(round((1.0 - min(max(ratio, 0.0), 1.0)) * _PRIORITY_SCALE))


def priority_to_ratio(priority: int) -> float:
    return 1.0 - priority / _PRIORITY_SCALE


def plan_vacuums(
    volume_states: dict, threshold: float, include_all: bool = False
) -> list[RepairTask]:
    """Vacuum planning over heartbeat-derived state.

    volume_states: {vid: [{url, collection, garbage_ratio, read_only,
    scrub_corrupt}, ...]} — one entry per live replica holder (the shape
    `Topology.replica_states` returns).

    One task per qualifying volume, kind="vacuum", highest garbage first.
    A volume qualifies when its LOWEST replica ratio clears the threshold
    (compaction commits on every replica or not at all) and no replica is
    read-only/quarantined (a read-only copy cannot replay the makeup
    diff; a quarantined one belongs to the repair plane, not vacuum).
    include_all skips the threshold gate (forced sweeps: the dispatcher's
    authoritative per-replica check still applies the threshold).
    """
    tasks = []
    for vid, replicas in volume_states.items():
        if not replicas:
            continue
        if any(r.get("read_only") or r.get("scrub_corrupt") for r in replicas):
            continue
        min_ratio = min(float(r.get("garbage_ratio", 0.0)) for r in replicas)
        if not include_all and min_ratio < threshold:
            continue
        tasks.append(
            RepairTask(
                kind="vacuum",
                vid=int(vid),
                collection=replicas[0].get("collection", ""),
                priority=garbage_priority(min_ratio),
                survivors=len(replicas),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks
