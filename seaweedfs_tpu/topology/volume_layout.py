"""VolumeLayout: writable/readonly tracking per (collection, rp, ttl)
(ref: weed/topology/volume_layout.go)."""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional

from .node import DataNode


class VolumeLayout:
    def __init__(self, replica_placement, ttl, volume_size_limit: int):
        self.replica_placement = replica_placement
        self.ttl = ttl
        self.volume_size_limit = volume_size_limit
        self.vid_to_locations: Dict[int, list[DataNode]] = {}
        self.writables: list[int] = []
        self.oversized: set[int] = set()
        self.readonly: set[int] = set()
        self._lock = threading.RLock()

    def register_volume(self, info: dict, dn: DataNode) -> None:
        vid = int(info["id"])
        with self._lock:
            locs = self.vid_to_locations.setdefault(vid, [])
            if dn not in locs:
                locs.append(dn)
            if info.get("read_only"):
                self.readonly.add(vid)
            else:
                self.readonly.discard(vid)
            if self._is_oversized(info):
                self.oversized.add(vid)
            self._remember_writable(vid, info)

    def unregister_volume(self, info: dict, dn: DataNode) -> None:
        vid = int(info["id"])
        with self._lock:
            locs = self.vid_to_locations.get(vid, [])
            if dn in locs:
                locs.remove(dn)
            if not locs:
                self.vid_to_locations.pop(vid, None)
                self._set_unwritable(vid)
            elif len(locs) < self.replica_placement.copy_count():
                # under-replicated volumes stop taking writes
                self._set_unwritable(vid)

    def _is_oversized(self, info: dict) -> bool:
        return int(info.get("size", 0)) >= self.volume_size_limit

    def _remember_writable(self, vid: int, info: dict) -> None:
        locs = self.vid_to_locations.get(vid, [])
        writable = (
            not info.get("read_only")
            and vid not in self.oversized
            and len(locs) >= self.replica_placement.copy_count()
        )
        if writable:
            if vid not in self.writables:
                self.writables.append(vid)
        else:
            self._set_unwritable(vid)

    def _set_unwritable(self, vid: int) -> None:
        if vid in self.writables:
            self.writables.remove(vid)

    def set_volume_unavailable(self, vid: int, dn: DataNode) -> None:
        with self._lock:
            locs = self.vid_to_locations.get(vid, [])
            if dn in locs:
                locs.remove(dn)
            if len(locs) < self.replica_placement.copy_count():
                self._set_unwritable(vid)
            if not locs:
                self.vid_to_locations.pop(vid, None)

    def set_volume_capacity_full(self, vid: int) -> None:
        with self._lock:
            self.oversized.add(vid)
            self._set_unwritable(vid)

    def lookup(self, vid: int) -> Optional[list[DataNode]]:
        with self._lock:
            locs = self.vid_to_locations.get(vid)
            return list(locs) if locs else None

    def has_writable_volume(self) -> bool:
        with self._lock:
            return len(self.writables) > 0

    def active_volume_count(self) -> int:
        with self._lock:
            return len(self.writables)

    def pick_for_write(self) -> tuple[int, list[DataNode]]:
        """Random writable volume + its replica locations
        (ref volume_layout.go PickForWrite)."""
        with self._lock:
            if not self.writables:
                raise LookupError("no writable volumes")
            vid = random.choice(self.writables)
            return vid, list(self.vid_to_locations[vid])

    def to_info(self) -> dict:
        with self._lock:
            return {
                "replication": str(self.replica_placement),
                "ttl": str(self.ttl),
                "writables": list(self.writables),
            }
