"""Topology tree nodes with capacity accounting rolled up the tree.

DataNode -> Rack -> DataCenter -> Topology (ref: weed/topology/node.go,
data_node.go, rack.go, data_center.go). Volume/EC-shard inventories live on
DataNodes; ancestors track aggregate slot counts for the placement solver.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, Optional

from ..storage.erasure_coding import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from ..storage.erasure_coding.ec_volume import ShardBits


class Node:
    def __init__(self, node_id: str):
        self.id = node_id
        self.parent: Optional[Node] = None
        self.children: Dict[str, Node] = {}
        self.volume_count = 0
        self.ec_shard_count = 0
        self.max_volume_count = 0
        self.max_volume_id = 0
        self._lock = threading.RLock()

    # --- capacity accounting (ref node.go UpAdjust*) ---
    def free_space(self) -> int:
        """Free volume slots; EC shards consume fractional slots
        (ref node.go FreeSpace: ecShardCount/TotalShards rounded up)."""
        free = self.max_volume_count - self.volume_count
        if self.ec_shard_count > 0:
            free -= (self.ec_shard_count + TOTAL_SHARDS_COUNT - 1) // TOTAL_SHARDS_COUNT
        return free

    def adjust_volume_count(self, delta: int) -> None:
        node: Optional[Node] = self
        while node is not None:
            node.volume_count += delta
            node = node.parent

    def adjust_ec_shard_count(self, delta: int) -> None:
        node: Optional[Node] = self
        while node is not None:
            node.ec_shard_count += delta
            node = node.parent

    def adjust_max_volume_count(self, delta: int) -> None:
        node: Optional[Node] = self
        while node is not None:
            node.max_volume_count += delta
            node = node.parent

    def adjust_max_volume_id(self, vid: int) -> None:
        node: Optional[Node] = self
        while node is not None:
            if vid > node.max_volume_id:
                node.max_volume_id = vid
            node = node.parent

    def link_child(self, child: "Node") -> None:
        with self._lock:
            if child.id not in self.children:
                self.children[child.id] = child
                child.parent = self
                self.adjust_max_volume_count(child.max_volume_count)
                self.adjust_volume_count(child.volume_count)
                self.adjust_ec_shard_count(child.ec_shard_count)
                self.adjust_max_volume_id(child.max_volume_id)

    def unlink_child(self, child_id: str) -> None:
        with self._lock:
            child = self.children.pop(child_id, None)
            if child is not None:
                self.adjust_max_volume_count(-child.max_volume_count)
                self.adjust_volume_count(-child.volume_count)
                self.adjust_ec_shard_count(-child.ec_shard_count)
                child.parent = None

    def descend_data_nodes(self) -> Iterable["DataNode"]:
        if isinstance(self, DataNode):
            yield self
            return
        for child in list(self.children.values()):
            yield from child.descend_data_nodes()


class DataNode(Node):
    """One volume server (ref: weed/topology/data_node.go)."""

    def __init__(self, node_id: str, url: str, public_url: str, max_volumes: int):
        super().__init__(node_id)
        self.url = url  # host:port of the HTTP data plane
        self.public_url = public_url or url
        self.max_volume_count = max_volumes
        self.volumes: Dict[int, dict] = {}  # vid -> volume info message
        self.ec_shards: Dict[int, ShardBits] = {}  # vid -> shard bits
        # vid -> decayed EC read heat this node last reported (lifecycle
        # plane; refreshed by full EC messages + the per-pulse heat tick)
        self.ec_heat: Dict[int, float] = {}
        self.last_seen = time.time()

    @property
    def rack(self) -> Optional["Rack"]:
        return self.parent  # type: ignore

    @property
    def data_center(self) -> Optional["DataCenter"]:
        return self.parent.parent if self.parent else None  # type: ignore

    def update_volumes(
        self, volume_infos: list[dict]
    ) -> tuple[list[dict], list[dict], list[tuple[dict, dict]]]:
        """Full-state sync; returns (new, deleted, changed) volume infos —
        changed as (old, new) pairs whose layout key (replication/ttl/
        collection) moved, e.g. after volume.configure.replication
        (ref data_node.go UpdateVolumes)."""
        incoming = {int(v["id"]): v for v in volume_infos}
        new, deleted, changed = [], [], []
        layout_key = lambda v: (
            v.get("collection", ""),
            v.get("replica_placement", 0),
            v.get("ttl", 0),
        )
        with self._lock:
            for vid in list(self.volumes):
                if vid not in incoming:
                    deleted.append(self.volumes.pop(vid))
                    self.adjust_volume_count(-1)
            for vid, info in incoming.items():
                if vid not in self.volumes:
                    new.append(info)
                    self.adjust_volume_count(1)
                    self.adjust_max_volume_id(vid)
                elif layout_key(self.volumes[vid]) != layout_key(info):
                    changed.append((self.volumes[vid], info))
                self.volumes[vid] = info
        return new, deleted, changed

    def delta_update_volumes(
        self, new_volumes: list[dict], deleted_volumes: list[dict]
    ) -> None:
        with self._lock:
            for info in deleted_volumes:
                if int(info["id"]) in self.volumes:
                    del self.volumes[int(info["id"])]
                    self.adjust_volume_count(-1)
            for info in new_volumes:
                vid = int(info["id"])
                if vid not in self.volumes:
                    self.adjust_volume_count(1)
                    self.adjust_max_volume_id(vid)
                self.volumes[vid] = info

    def update_ec_shards(
        self, shard_infos: list[dict]
    ) -> tuple[list[tuple[int, str, ShardBits]], list[tuple[int, str, ShardBits]]]:
        """Full-state EC sync; returns (new, deleted) (vid, collection, bits)."""
        incoming: Dict[int, tuple[str, ShardBits]] = {}
        for m in shard_infos:
            incoming[int(m["id"])] = (
                m.get("collection", ""),
                ShardBits(int(m["ec_index_bits"])),
            )
        new, deleted = [], []
        with self._lock:
            for vid in list(self.ec_shards):
                if vid not in incoming:
                    bits = self.ec_shards.pop(vid)
                    self.adjust_ec_shard_count(-bits.count())
                    deleted.append((vid, "", bits))
            for vid, (collection, bits) in incoming.items():
                old = self.ec_shards.get(vid, ShardBits())
                added = bits.minus(old)
                removed = old.minus(bits)
                if added.bits:
                    new.append((vid, collection, added))
                if removed.bits:
                    deleted.append((vid, collection, removed))
                self.adjust_ec_shard_count(bits.count() - old.count())
                if bits.bits:
                    self.ec_shards[vid] = bits
                else:
                    self.ec_shards.pop(vid, None)
        return new, deleted

    def delta_update_ec_shards(
        self,
        new_shards: list[tuple[int, str, ShardBits]],
        deleted_shards: list[tuple[int, str, ShardBits]],
    ) -> None:
        with self._lock:
            for vid, _c, bits in new_shards:
                old = self.ec_shards.get(vid, ShardBits())
                merged = old.plus(bits)
                self.adjust_ec_shard_count(merged.count() - old.count())
                self.ec_shards[vid] = merged
            for vid, _c, bits in deleted_shards:
                old = self.ec_shards.get(vid, ShardBits())
                remaining = old.minus(bits)
                self.adjust_ec_shard_count(remaining.count() - old.count())
                if remaining.bits:
                    self.ec_shards[vid] = remaining
                else:
                    self.ec_shards.pop(vid, None)

    def to_info(self) -> dict:
        return {
            "id": self.id,
            "url": self.url,
            "public_url": self.public_url,
            "volume_count": self.volume_count,
            "max_volume_count": self.max_volume_count,
            "ec_shard_count": self.ec_shard_count,
            "free_space": self.free_space(),
            "volumes": list(self.volumes.values()),
            "ec_shards": [
                {"id": vid, "ec_index_bits": bits.bits}
                for vid, bits in self.ec_shards.items()
            ],
        }


class Rack(Node):
    def get_or_create_data_node(
        self, node_id: str, url: str, public_url: str, max_volumes: int
    ) -> DataNode:
        with self._lock:
            dn = self.children.get(node_id)
            if isinstance(dn, DataNode):
                dn.last_seen = time.time()
                return dn
            dn = DataNode(node_id, url, public_url, max_volumes)
            self.link_child(dn)
            return dn


class DataCenter(Node):
    def get_or_create_rack(self, rack_id: str) -> Rack:
        with self._lock:
            r = self.children.get(rack_id)
            if isinstance(r, Rack):
                return r
            r = Rack(rack_id)
            self.link_child(r)
            return r
