"""Replica placement solver + volume growth.

Finds (1 + x + y + z) empty slots honoring the xyz ReplicaPlacement: pick a
main data center / rack / server weighted by free slots, then the other-DC,
other-rack and same-rack copies (ref: weed/topology/volume_growth.go:70-130).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from ..storage.super_block import ReplicaPlacement
from ..storage.ttl import EMPTY_TTL, TTL
from .node import DataCenter, DataNode, Node, Rack


@dataclass
class GrowOption:
    collection: str = ""
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = EMPTY_TTL
    preallocate: int = 0
    data_center: str = ""
    rack: str = ""
    data_node: str = ""
    memory_map_max_size_mb: int = 0


def grow_count_for_copy_level(copy_count: int) -> int:
    """How many volumes to grow per request (ref volume_growth.go:51-68)."""
    return {1: 7, 2: 6, 3: 3}.get(copy_count, 1)


def _weighted_pick(candidates: list[Node]) -> Optional[Node]:
    """Random pick weighted by free slots."""
    weights = [max(c.free_space(), 0) for c in candidates]
    total = sum(weights)
    if total <= 0:
        return None
    r = random.randrange(total)
    for c, w in zip(candidates, weights):
        if r < w:
            return c
        r -= w
    return candidates[-1]


class NoFreeSpaceError(Exception):
    pass


class VolumeGrowth:
    def find_empty_slots(
        self, topo, option: GrowOption
    ) -> list[DataNode]:
        """Servers (first = main) able to host one new volume's replicas."""
        rp = option.replica_placement

        # main DC: needs >= diff_dc_count other DCs and enough local capacity
        dcs = [
            dc
            for dc in topo.children.values()
            if isinstance(dc, DataCenter)
            and (not option.data_center or dc.id == option.data_center)
            and dc.free_space() >= rp.diff_rack_count + rp.same_rack_count + 1
            and len(dc.children) > rp.diff_rack_count
        ]
        other_dcs_needed = rp.diff_data_center_count
        dcs = [
            dc
            for dc in dcs
            if sum(
                1
                for other in topo.children.values()
                if other is not dc and other.free_space() > 0
            )
            >= other_dcs_needed
        ]
        main_dc = _weighted_pick(dcs)  # type: ignore[arg-type]
        if main_dc is None:
            raise NoFreeSpaceError("no data center with enough free slots")

        # main rack
        racks = [
            r
            for r in main_dc.children.values()
            if isinstance(r, Rack)
            and (not option.rack or r.id == option.rack)
            and r.free_space() >= rp.same_rack_count + 1
            and len(r.children) > rp.same_rack_count
        ]
        racks = [
            r
            for r in racks
            if sum(
                1
                for other in main_dc.children.values()
                if other is not r and other.free_space() > 0
            )
            >= rp.diff_rack_count
        ]
        main_rack = _weighted_pick(racks)  # type: ignore[arg-type]
        if main_rack is None:
            raise NoFreeSpaceError("no rack with enough free slots")

        # main server + same-rack copies
        servers = [
            dn
            for dn in main_rack.children.values()
            if isinstance(dn, DataNode)
            and (not option.data_node or dn.id == option.data_node)
            and dn.free_space() > 0
        ]
        if len(servers) < rp.same_rack_count + 1:
            raise NoFreeSpaceError("not enough servers in rack")
        main_server = _weighted_pick(servers)  # type: ignore[arg-type]
        if main_server is None:
            raise NoFreeSpaceError("no server with free slots")
        chosen = [main_server]
        rest = [s for s in servers if s is not main_server]
        random.shuffle(rest)
        chosen.extend(rest[: rp.same_rack_count])
        if len(chosen) < rp.same_rack_count + 1:
            raise NoFreeSpaceError("not enough same-rack replicas")

        # other racks in the main DC
        other_racks = [
            r
            for r in main_dc.children.values()
            if r is not main_rack and r.free_space() > 0
        ]
        random.shuffle(other_racks)
        for r in other_racks[: rp.diff_rack_count]:
            dn = _weighted_pick(
                [s for s in r.descend_data_nodes() if s.free_space() > 0]
            )
            if dn is None:
                raise NoFreeSpaceError("no server in other rack")
            chosen.append(dn)
        if len(chosen) < rp.same_rack_count + 1 + rp.diff_rack_count:
            raise NoFreeSpaceError("not enough diff-rack replicas")

        # other data centers
        other_dcs = [
            dc for dc in topo.children.values() if dc is not main_dc and dc.free_space() > 0
        ]
        random.shuffle(other_dcs)
        for dc in other_dcs[: rp.diff_data_center_count]:
            dn = _weighted_pick(
                [s for s in dc.descend_data_nodes() if s.free_space() > 0]
            )
            if dn is None:
                raise NoFreeSpaceError("no server in other data center")
            chosen.append(dn)
        if len(chosen) < rp.copy_count():
            raise NoFreeSpaceError("not enough replicas")
        return chosen

    async def grow_by_count(
        self, count: int, topo, option: GrowOption, allocate_fn
    ) -> int:
        """Grow up to `count` volumes; allocate_fn(vid, option, servers) is an
        async callback that performs the AllocateVolume RPCs. Returns how many
        volumes were created."""
        grown = 0
        for _ in range(count):
            try:
                servers = self.find_empty_slots(topo, option)
            except NoFreeSpaceError:
                break
            vid = topo.next_volume_id()
            ok = await allocate_fn(vid, option, servers)
            if not ok:
                break
            for dn in servers:
                topo.register_volume(
                    {
                        "id": vid,
                        "size": 0,
                        "collection": option.collection,
                        "replica_placement": option.replica_placement.to_byte(),
                        "ttl": option.ttl.to_u32(),
                        "read_only": False,
                        "version": 3,
                    },
                    dn,
                )
            grown += 1
        return grown
