"""DC/rack-aware placement policy: violation detection + repair planning.

The volume-growth solver (`volume_growth.find_empty_slots`) places NEW
volumes according to the xyz `ReplicaPlacement` semantics (ref
weed/topology/volume_growth.go): one main rack holding 1+z copies, y
more racks in the main DC with one copy each, x other DCs with one copy
each. Nothing re-checked EXISTING placements: a volume grown before a
rack label changed, re-replicated by anti-entropy onto whatever node was
free, or EC-encoded with every shard on one rack silently violates the
spread the policy promises — and a single rack loss then takes out more
copies/shards than the redundancy budget allows.

This module is the pure planning half (the master's anti-entropy round
dispatches, mirroring `topology/repair.py`):

- `plan_replica_spread` checks each volume's live holders against its
  layout's `ReplicaPlacement` and, when the spread is violated, proposes
  ONE move per volume per scan (copy to a better-placed node, then drop
  the source copy) — repeated scans converge, and single-step moves keep
  every intermediate state at full copy count.
- `plan_ec_domain_spread` flags EC volumes where one failure domain
  (rack) holds more shards than the volume can lose (> parity): losing
  that rack would be unrecoverable. The proposed move rides the same
  shard-move RPCs as `ec.balance`.

Both planners emit `RepairTask`s into the existing `RepairQueue` with
LOWER priority than data-loss repairs (placement is about the NEXT
failure; missing shards are about the current one), plus a violation
report for `geo.status` / `PlacementStatus -run`.
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Optional

from ..storage.erasure_coding import DATA_SHARDS_COUNT
from ..storage.super_block import ReplicaPlacement
from .repair import RepairTask

# placement tasks sort after data-loss repairs no matter how many
# survivors those report: priority is "surviving copies, fewest first"
# and real clusters never exceed a few replicas/shards
PLACEMENT_PRIORITY = 1 << 20


def replica_spread_ok(
    rp: ReplicaPlacement, domains: list[tuple[str, str]]
) -> bool:
    """Whether (dc, rack) holder domains satisfy the xyz placement: some
    DC holds 1+z+y copies (one rack 1+z, y other racks 1 each) and x
    other DCs hold exactly one copy each. Judged only at full copy
    count — under/over-replication is the replica planner's concern."""
    x, y, z = (
        rp.diff_data_center_count,
        rp.diff_rack_count,
        rp.same_rack_count,
    )
    if len(domains) != rp.copy_count():
        return True
    dc_racks: dict[str, Counter] = defaultdict(Counter)
    for dc, rack in domains:
        dc_racks[dc][rack] += 1
    if len(dc_racks) != x + 1:
        return False
    for main_dc, racks in dc_racks.items():
        if sum(racks.values()) != 1 + z + y:
            continue
        others_ok = all(
            sum(r.values()) == 1
            for dc, r in dc_racks.items()
            if dc != main_dc
        )
        main_ok = (
            len(racks) == y + 1
            and sorted(racks.values()) == [1] * y + [1 + z]
        )
        if others_ok and main_ok:
            return True
    return False


def _pick_target(
    candidates: list[dict],
    exclude_urls: set,
    want_dc: Optional[set] = None,
    want_rack_not: Optional[set] = None,
    same_dc: Optional[str] = None,
) -> Optional[dict]:
    """Most-free candidate node matching the domain constraints: in one
    of `want_dc` (when given), NOT in `want_rack_not` racks, in `same_dc`
    (when given), and not already a holder."""
    best = None
    for c in candidates:
        if c["url"] in exclude_urls or c.get("free", 0) <= 0:
            continue
        if want_dc is not None and c["dc"] not in want_dc:
            continue
        if same_dc is not None and c["dc"] != same_dc:
            continue
        if want_rack_not is not None and (c["dc"], c["rack"]) in want_rack_not:
            continue
        if best is None or c.get("free", 0) > best.get("free", 0):
            best = c
    return best


def plan_replica_spread(
    placement_states: list[dict], candidates: list[dict]
) -> tuple[list[dict], list[RepairTask]]:
    """-> (violations, placement-move tasks).

    placement_states: [{vid, collection, replica_placement (byte),
    holders: [{url, dc, rack}]}] restricted to live holders;
    candidates: [{url, dc, rack, free}] — every live node.
    """
    violations: list[dict] = []
    tasks: list[RepairTask] = []
    for st in placement_states:
        rp = ReplicaPlacement.from_byte(int(st["replica_placement"]))
        holders = st["holders"]
        domains = [(h["dc"], h["rack"]) for h in holders]
        if replica_spread_ok(rp, domains):
            continue
        violation = {
            "kind": "replica_spread",
            "volume_id": int(st["vid"]),
            "collection": st.get("collection", ""),
            "replication": str(rp),
            "holders": [
                f"{h['url']}({h['dc']}/{h['rack']})" for h in holders
            ],
        }
        violations.append(violation)
        move = _plan_one_replica_move(rp, holders, candidates)
        if move is None:
            violation["repair"] = "no candidate node restores the spread"
            continue
        source, target = move
        violation["repair"] = f"move {source} -> {target}"
        tasks.append(
            RepairTask(
                kind="placement_move",
                vid=int(st["vid"]),
                collection=st.get("collection", ""),
                priority=PLACEMENT_PRIORITY,
                survivors=len(holders),
                target=target,
                source=source,
            )
        )
    return violations, tasks


def _plan_one_replica_move(
    rp: ReplicaPlacement, holders: list[dict], candidates: list[dict]
) -> Optional[tuple[str, str]]:
    """One (source_url, target_url) move toward a valid spread, or None.

    Greedy: fix DC spread first (move a copy out of the most-loaded DC
    into a DC holding none), then rack spread inside the main DC (move a
    copy out of the most-loaded rack into a main-DC rack holding none).
    One move per scan: every intermediate state keeps full copy count,
    and the next scan re-plans from observed (not predicted) state.
    """
    x, y = rp.diff_data_center_count, rp.diff_rack_count
    holder_urls = {h["url"] for h in holders}
    by_dc: dict[str, list[dict]] = defaultdict(list)
    for h in holders:
        by_dc[h["dc"]].append(h)
    if len(by_dc) < x + 1:
        # too few DCs: source = a copy from the DC with the most copies
        # (tie-broken toward its most-loaded rack), target = any node in
        # a DC currently holding nothing
        src_dc = max(by_dc, key=lambda d: len(by_dc[d]))
        racks = Counter((h["dc"], h["rack"]) for h in by_dc[src_dc])
        src = max(
            by_dc[src_dc], key=lambda h: racks[(h["dc"], h["rack"])]
        )
        target = _pick_target(
            candidates,
            holder_urls,
            want_dc={
                c["dc"] for c in candidates if c["dc"] not in by_dc
            },
        )
        return (src["url"], target["url"]) if target else None
    # enough DCs (or too many — then rack logic below still finds the
    # overloaded group): fix rack spread inside the main (largest) DC
    main_dc = max(by_dc, key=lambda d: len(by_dc[d]))
    rack_counts = Counter(h["rack"] for h in by_dc[main_dc])
    if len(rack_counts) >= y + 1 and len(by_dc) == x + 1:
        # spread is wrong in a shape one greedy move can't name (e.g.
        # two racks both above 1+z with no free rack) — still try:
        # move from the most-loaded rack to an unused main-DC rack
        pass
    src_rack = max(rack_counts, key=lambda r: rack_counts[r])
    src = next(h for h in by_dc[main_dc] if h["rack"] == src_rack)
    used_racks = {(main_dc, r) for r in rack_counts}
    target = _pick_target(
        candidates, holder_urls, same_dc=main_dc, want_rack_not=used_racks
    )
    if target is None and len(by_dc) > x + 1:
        # too MANY DCs: consolidate one stray copy into the main DC
        stray_dc = min(by_dc, key=lambda d: len(by_dc[d]))
        src = by_dc[stray_dc][0]
        target = _pick_target(
            candidates, holder_urls, same_dc=main_dc
        )
    return (src["url"], target["url"]) if target else None


def plan_ec_domain_spread(
    ec_states: list[dict], candidates: list[dict]
) -> tuple[list[dict], list[RepairTask]]:
    """-> (violations, ec placement-move tasks).

    ec_states: the repair planner's shape ({vid, collection,
    total_shards, holders: {shard_id: [urls]}}, optionally
    parity_shards); candidates: [{url, dc, rack, free}]. A failure
    domain (rack) holding more than `parity` shards is a data-loss
    domain: losing it loses more shards than decode can tolerate."""
    domain_of = {c["url"]: (c["dc"], c["rack"]) for c in candidates}
    violations: list[dict] = []
    tasks: list[RepairTask] = []
    for st in ec_states:
        total = int(st["total_shards"])
        parity = int(
            st.get("parity_shards")
            or max(total - DATA_SHARDS_COUNT, 1)
        )
        holders = st["holders"]
        shard_domains: dict[tuple, list[int]] = defaultdict(list)
        shard_home: dict[int, str] = {}
        for sid, urls in holders.items():
            if not urls:
                continue
            url = urls[0]
            shard_home[int(sid)] = url
            dom = domain_of.get(url)
            if dom is not None:
                shard_domains[dom].append(int(sid))
        if len({d for d in shard_domains}) <= 1 and len(candidates) <= 1:
            continue  # single-domain cluster: nowhere to spread to
        overloaded = {
            dom: sids
            for dom, sids in shard_domains.items()
            if len(sids) > parity
        }
        if not overloaded or len(shard_domains) == 0:
            continue
        if len({(c["dc"], c["rack"]) for c in candidates}) <= 1:
            continue  # policy unsatisfiable on this topology: report-only
        dom, sids = max(overloaded.items(), key=lambda kv: len(kv[1]))
        violation = {
            "kind": "ec_domain",
            "volume_id": int(st["vid"]),
            "collection": st.get("collection", ""),
            "domain": f"{dom[0]}/{dom[1]}",
            "shards_in_domain": len(sids),
            "parity_shards": parity,
        }
        violations.append(violation)
        sid = max(sids)
        source = shard_home[sid]
        # only move INTO a domain that stays within the loss budget after
        # the move — with every other domain already at parity the policy
        # is unsatisfiable on this topology and shuffling shards between
        # overloaded racks would just oscillate scan over scan
        room = [
            c
            for c in candidates
            if len(shard_domains.get((c["dc"], c["rack"]), [])) < parity
        ]
        target = _pick_target(room, {source}, want_rack_not={dom})
        if target is None:
            violation["repair"] = (
                "no candidate domain has shard room below parity"
            )
            continue
        violation["repair"] = (
            f"move shard {sid} {source} -> {target['url']}"
        )
        tasks.append(
            RepairTask(
                kind="ec_placement",
                vid=int(st["vid"]),
                collection=st.get("collection", ""),
                priority=PLACEMENT_PRIORITY,
                missing=[sid],  # the shard to move
                survivors=total,
                target=target["url"],
                source=source,
            )
        )
    return violations, tasks
