"""Cluster topology: DataCenter/Rack/DataNode tree, volume layouts,
replica placement, EC shard registry (ref: weed/topology/)."""

from .node import DataCenter, DataNode, Rack
from .topology import Topology
from .volume_layout import VolumeLayout
from .volume_growth import VolumeGrowth, GrowOption

__all__ = [
    "DataCenter",
    "DataNode",
    "Rack",
    "Topology",
    "VolumeLayout",
    "VolumeGrowth",
    "GrowOption",
]
