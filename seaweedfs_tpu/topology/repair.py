"""Master-side repair scheduling: the decision half of the anti-entropy
plane (planning is pure and unit-testable; dispatch lives in
`server/master.py`).

Heartbeats are the sensor: a node silent past the grace period no longer
counts as a holder, so its EC shards show up as missing; a scrub
quarantine arrives as `scrub_corrupt` on a volume message; a stale
replica shows a digest that disagrees while its append frontier trails.
Each finding becomes a `RepairTask` in a prioritized queue — EC volumes
closest to unrecoverable first (fewest surviving shards), then replica
repairs — dispatched under a concurrency cap with full-jitter backoff on
repeated failures so a broken target cannot hot-loop the scheduler.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional

from ..util.backoff import BackoffPolicy
from ..util.metrics import REPAIR_QUEUE_DEPTH

# backoff between attempts of a failing repair: starts at ~2s, caps at 60s
REPAIR_BACKOFF = BackoffPolicy(base=2.0, cap=60.0, multiplier=2.0, attempts=1 << 30)


@dataclass
class RepairTask:
    kind: str  # ec_rebuild | replica_recopy | tail_sync
    vid: int
    collection: str = ""
    priority: int = 1 << 30  # surviving copies/shards: fewest first
    missing: list = field(default_factory=list)  # ec_rebuild: shard ids
    survivors: int = 0
    target: str = ""  # replica repairs: the node being fixed
    source: str = ""  # replica repairs: the healthy donor
    attempts: int = 0
    not_before: float = 0.0

    @property
    def key(self) -> tuple:
        return (self.kind, self.vid, self.target)

    def to_info(self) -> dict:
        return {
            "kind": self.kind,
            "volume_id": self.vid,
            "collection": self.collection,
            "priority": self.priority,
            "missing": list(self.missing),
            "survivors": self.survivors,
            "target": self.target,
            "source": self.source,
            "attempts": self.attempts,
            "not_before": self.not_before,
        }


class RepairQueue:
    """Priority queue of repair tasks, deduped by (kind, vid, target).

    `offer` keeps the retry state (attempts/not_before) of a task the
    planner re-discovers every scan — re-planning must not reset backoff.
    `pop_ready` returns up to `limit` tasks whose backoff window has
    passed, fewest-survivors-first; `reschedule_failure` requeues with a
    full-jitter delay. The live depth is mirrored into
    `repair_queue_depth` so draining to zero is externally observable."""

    def __init__(
        self,
        policy: BackoffPolicy = REPAIR_BACKOFF,
        rng: Optional[random.Random] = None,
        depth_gauge=REPAIR_QUEUE_DEPTH,
    ):
        self.policy = policy
        self.rng = rng or random.Random()
        self._tasks: dict[tuple, RepairTask] = {}
        # the vacuum scheduler reuses this queue with its own depth gauge
        self._depth_gauge = depth_gauge

    def _publish_depth(self) -> None:
        self._depth_gauge.set(len(self._tasks))

    def offer(self, task: RepairTask) -> bool:
        existing = self._tasks.get(task.key)
        if existing is not None:
            # refresh the plan facts, keep the retry state
            task.attempts = existing.attempts
            task.not_before = existing.not_before
        self._tasks[task.key] = task
        self._publish_depth()
        return existing is None

    def discard(self, key: tuple) -> None:
        self._tasks.pop(key, None)
        self._publish_depth()

    def prune(self, valid_keys: set) -> None:
        """Drop tasks the latest scan no longer justifies (the node came
        back, the shard re-registered) — self-healing must also self-calm."""
        for key in [k for k in self._tasks if k not in valid_keys]:
            self._tasks.pop(key)
        self._publish_depth()

    def retry_keys(self) -> set:
        """Keys of tasks that have already failed at least once (they sit
        in a backoff window). The vacuum scheduler exempts these from
        pruning: a forced sweep's failed task must survive background
        scans whose (stale or higher-threshold) plan wouldn't re-justify
        it — the caller was promised a retry."""
        return {k for k, t in self._tasks.items() if t.attempts > 0}

    def pop_ready(self, now: float, limit: int) -> list[RepairTask]:
        ready = sorted(
            (t for t in self._tasks.values() if t.not_before <= now),
            key=lambda t: (t.priority, t.vid, t.kind),
        )[:limit]
        for t in ready:
            self._tasks.pop(t.key, None)
        self._publish_depth()
        return ready

    def reschedule_failure(self, task: RepairTask, now: float) -> None:
        task.attempts += 1
        task.not_before = now + self.policy.delay(task.attempts - 1, self.rng)
        self._tasks[task.key] = task
        self._publish_depth()

    def depth(self) -> int:
        return len(self._tasks)

    def snapshot(self) -> list[dict]:
        return [
            t.to_info()
            for t in sorted(
                self._tasks.values(), key=lambda t: (t.priority, t.vid)
            )
        ]


# ---------------------------------------------------------------- planners --


def plan_ec_repairs(ec_states: list[dict]) -> list[RepairTask]:
    """EC repair planning over heartbeat-derived state.

    ec_states: [{vid, collection, total_shards, data_shards?, holders:
    {shard_id: [live urls]}}] where `holders` already excludes nodes
    silent past the grace period. A volume missing shards becomes one
    task whose priority is its surviving-shard count — the queue then
    repairs the volumes closest to data loss first."""
    tasks = []
    for st in ec_states:
        total = int(st["total_shards"])
        holders = st["holders"]
        present = [s for s in range(total) if holders.get(s)]
        missing = [s for s in range(total) if not holders.get(s)]
        if not missing:
            continue
        tasks.append(
            RepairTask(
                kind="ec_rebuild",
                vid=int(st["vid"]),
                collection=st.get("collection", ""),
                priority=len(present),
                missing=missing,
                survivors=len(present),
            )
        )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks


def find_unresolved_divergence(volume_states: dict) -> list[int]:
    """Volumes whose healthy replicas disagree on digest while their
    append frontiers are EQUAL — content diverged in the middle (e.g. a
    torn-tail truncation later papered over by new appends), which the
    tail path cannot fix and no automatic repair can arbitrate. These
    must be VISIBLE (gauge + log) rather than silently skipped."""
    out = []
    for vid, replicas in volume_states.items():
        healthy = [r for r in replicas if not r.get("scrub_corrupt")]
        if len(healthy) < 2:
            continue
        top = max(int(r.get("append_at_ns", 0)) for r in healthy)
        at_top = [
            int(r.get("content_digest", 0))
            for r in healthy
            if int(r.get("append_at_ns", 0)) == top
        ]
        if len(at_top) > 1 and len(set(at_top)) > 1:
            out.append(vid)
    return sorted(out)


def plan_replica_repairs(volume_states: dict) -> list[RepairTask]:
    """Replica anti-entropy planning.

    volume_states: {vid: [{url, collection, content_digest, append_at_ns,
    scrub_corrupt, read_only}, ...]} — one entry per live replica holder.

    Two findings, in repair order:
    - a scrub-quarantined replica with at least one healthy peer is
      re-copied whole from that peer (`replica_recopy`): bit rot cannot be
      fixed by appending;
    - replicas whose digests disagree while their append frontier trails
      the freshest copy are caught up through the incremental tail path
      (`tail_sync`) — the cheap fix for a replica that missed writes.
    """
    tasks = []
    for vid, replicas in volume_states.items():
        if len(replicas) < 2:
            continue
        healthy = [r for r in replicas if not r.get("scrub_corrupt")]
        if not healthy:
            continue  # nothing trustworthy to copy from
        freshest = max(healthy, key=lambda r: int(r.get("append_at_ns", 0)))
        for r in replicas:
            if r.get("scrub_corrupt"):
                tasks.append(
                    RepairTask(
                        kind="replica_recopy",
                        vid=vid,
                        collection=r.get("collection", ""),
                        priority=len(healthy),
                        survivors=len(healthy),
                        target=r["url"],
                        source=freshest["url"],
                    )
                )
                continue
            if (
                int(r.get("content_digest", 0))
                != int(freshest.get("content_digest", 0))
                and int(r.get("append_at_ns", 0))
                < int(freshest.get("append_at_ns", 0))
            ):
                tasks.append(
                    RepairTask(
                        kind="tail_sync",
                        vid=vid,
                        collection=r.get("collection", ""),
                        priority=len(healthy),
                        survivors=len(healthy),
                        target=r["url"],
                        source=freshest["url"],
                    )
                )
    tasks.sort(key=lambda t: (t.priority, t.vid))
    return tasks
