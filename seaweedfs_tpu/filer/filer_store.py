"""Pluggable filer stores (ref: weed/filer2/filerstore.go:12-31).

Interface: insert/update/find/delete/delete_children/list by (directory,
name). Three implementations: in-memory dict (ref memdb store), sqlite
(standing in for the reference's leveldb/mysql/postgres family — same
abstract-sql shape, ref weed/filer2/abstract_sql/), and an append-only
log store (WAL + memory index, standing in for the leveldb2 family —
durable writes without a database dependency)."""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Optional, Protocol

from .entry import Entry


class FilerStore(Protocol):
    def insert_entry(self, entry: Entry) -> None: ...
    def update_entry(self, entry: Entry) -> None: ...
    def find_entry(self, full_path: str) -> Optional[Entry]: ...
    def delete_entry(self, full_path: str) -> None: ...
    def delete_folder_children(self, full_path: str) -> None: ...
    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> list[Entry]: ...


def _split(full_path: str) -> tuple[str, str]:
    if full_path == "/":
        return "", "/"
    d, _, name = full_path.rstrip("/").rpartition("/")
    return d or "/", name


class MemoryFilerStore:
    def __init__(self):
        # directory -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {}
        self._lock = threading.RLock()

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        with self._lock:
            self._dirs.setdefault(d, {})[name] = entry

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Optional[Entry]:
        d, name = _split(full_path)
        with self._lock:
            return self._dirs.get(d, {}).get(name)

    def delete_entry(self, full_path: str) -> None:
        d, name = _split(full_path)
        with self._lock:
            self._dirs.get(d, {}).pop(name, None)

    def delete_folder_children(self, full_path: str) -> None:
        prefix = full_path.rstrip("/")
        with self._lock:
            self._dirs.pop(prefix, None)
            for d in [k for k in self._dirs if k.startswith(prefix + "/")]:
                self._dirs.pop(d, None)

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> list[Entry]:
        with self._lock:
            names = sorted(self._dirs.get(dir_path.rstrip("/") or "/", {}))
            out = []
            for name in names:
                if start_file_name:
                    if inclusive and name < start_file_name:
                        continue
                    if not inclusive and name <= start_file_name:
                        continue
                out.append(self._dirs[dir_path.rstrip("/") or "/"][name])
                if len(out) >= limit:
                    break
            return out


class SqliteFilerStore:
    """Durable store with the abstract-sql schema shape
    (dirhash+name keyed rows, ref weed/filer2/abstract_sql)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS filemeta (
                directory TEXT NOT NULL,
                name TEXT NOT NULL,
                meta TEXT NOT NULL,
                PRIMARY KEY (directory, name)
            )"""
        )
        self._conn.commit()

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        with self._lock:
            self._conn.execute(
                "REPLACE INTO filemeta (directory, name, meta) VALUES (?,?,?)",
                (d, name, json.dumps(entry.to_dict())),
            )
            self._conn.commit()

    update_entry = insert_entry

    def find_entry(self, full_path: str) -> Optional[Entry]:
        d, name = _split(full_path)
        with self._lock:
            row = self._conn.execute(
                "SELECT meta FROM filemeta WHERE directory=? AND name=?",
                (d, name),
            ).fetchone()
        return Entry.from_dict(json.loads(row[0])) if row else None

    def delete_entry(self, full_path: str) -> None:
        d, name = _split(full_path)
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE directory=? AND name=?", (d, name)
            )
            self._conn.commit()

    def delete_folder_children(self, full_path: str) -> None:
        prefix = full_path.rstrip("/")
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE directory=? OR directory LIKE ?",
                (prefix, prefix + "/%"),
            )
            self._conn.commit()

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> list[Entry]:
        op = ">=" if inclusive else ">"
        with self._lock:
            rows = self._conn.execute(
                f"SELECT meta FROM filemeta WHERE directory=? AND name {op} ? "
                "ORDER BY name LIMIT ?",
                (dir_path.rstrip("/") or "/", start_file_name, limit),
            ).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]


class LogFilerStore(MemoryFilerStore):
    """Append-only log store: every mutation appends a msgpack record to a
    WAL; reads serve from the in-memory index. Open replays the log, then
    compacts it to just the live entries (the leveldb2-class durability
    role, ref weed/filer2/leveldb2, without a database dependency)."""

    def __init__(self, path: str):
        super().__init__()
        import msgpack

        self._path = path
        self._packer = msgpack.Packer(use_bin_type=True)
        # replay
        import os

        if os.path.exists(path):
            with open(path, "rb") as f:
                unpacker = msgpack.Unpacker(f, raw=False)
                for rec in unpacker:
                    op = rec.get("op")
                    if op == "put":
                        super().insert_entry(Entry.from_dict(rec["entry"]))
                    elif op == "del":
                        super().delete_entry(rec["path"])
                    elif op == "delchildren":
                        super().delete_folder_children(rec["path"])
        self._compact()
        self._f = open(path, "ab")

    def _compact(self) -> None:
        """Rewrite the log with only live entries (atomic replace)."""
        import os

        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            with self._lock:
                for d in sorted(self._dirs):
                    for name in sorted(self._dirs[d]):
                        f.write(
                            self._packer.pack(
                                {
                                    "op": "put",
                                    "entry": self._dirs[d][name].to_dict(),
                                }
                            )
                        )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def _append(self, rec: dict) -> None:
        import os

        self._f.write(self._packer.pack(rec))
        self._f.flush()
        os.fsync(self._f.fileno())  # acknowledged mutations survive a crash

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            super().insert_entry(entry)
            self._append({"op": "put", "entry": entry.to_dict()})

    update_entry = insert_entry

    def delete_entry(self, full_path: str) -> None:
        with self._lock:
            super().delete_entry(full_path)
            self._append({"op": "del", "path": full_path})

    def delete_folder_children(self, full_path: str) -> None:
        with self._lock:
            super().delete_folder_children(full_path)
            self._append({"op": "delchildren", "path": full_path})

    def close(self) -> None:
        self._f.close()
