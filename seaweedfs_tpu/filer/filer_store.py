"""Pluggable filer stores (ref: weed/filer2/filerstore.go:12-31).

Interface: insert/update/find/delete/delete_children/list by (directory,
name). Three implementations: in-memory dict (ref memdb store), sqlite
(standing in for the reference's leveldb/mysql/postgres family — same
abstract-sql shape, ref weed/filer2/abstract_sql/), and an append-only
log store (WAL + memory index, standing in for the leveldb2 family —
durable writes without a database dependency)."""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Optional, Protocol

from .entry import Entry


class FilerStore(Protocol):
    def insert_entry(self, entry: Entry) -> None: ...
    def insert_many(self, entries: list[Entry]) -> None: ...
    def update_entry(self, entry: Entry) -> None: ...
    def find_entry(self, full_path: str) -> Optional[Entry]: ...
    def delete_entry(self, full_path: str) -> None: ...
    def delete_folder_children(self, full_path: str) -> None: ...
    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> list[Entry]: ...


def _split(full_path: str) -> tuple[str, str]:
    if full_path == "/":
        return "", "/"
    d, _, name = full_path.rstrip("/").rpartition("/")
    return d or "/", name


class MemoryFilerStore:
    def __init__(self):
        # directory -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {}
        self._lock = threading.RLock()
        # store round-trips taken by the write path: one per
        # insert_entry call, one per insert_many FLUSH (regardless of
        # batch width). The write-gate bench's "counted, not projected"
        # coalescing evidence — every store kind maintains it.
        self.write_rounds = 0

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        with self._lock:
            self.write_rounds += 1
            self._dirs.setdefault(d, {})[name] = entry

    update_entry = insert_entry

    def insert_many(self, entries: list[Entry]) -> None:
        """Batched upsert: many entries under ONE lock acquisition —
        the write-side twin of find_many (gate-batched write seam)."""
        with self._lock:
            self.write_rounds += 1
            for entry in entries:
                d, name = _split(entry.full_path)
                self._dirs.setdefault(d, {})[name] = entry

    def find_entry(self, full_path: str) -> Optional[Entry]:
        d, name = _split(full_path)
        with self._lock:
            return self._dirs.get(d, {}).get(name)

    def find_many(self, paths: list[str]) -> dict[str, Entry]:
        """Batched probe: many paths under ONE lock acquisition — the
        gate-batched lookup seam every store kind offers."""
        out: dict[str, Entry] = {}
        with self._lock:
            for p in paths:
                d, name = _split(p)
                e = self._dirs.get(d, {}).get(name)
                if e is not None:
                    out[p] = e
        return out

    def iter_all(self):
        """Every (directory, name, Entry), per-directory sorted — the
        sharded store's rebalance/cleanup bulk accessor."""
        with self._lock:
            snap = [
                (d, name, self._dirs[d][name])
                for d in sorted(self._dirs)
                for name in sorted(self._dirs[d])
            ]
        return iter(snap)

    def delete_entry(self, full_path: str) -> None:
        d, name = _split(full_path)
        with self._lock:
            self._dirs.get(d, {}).pop(name, None)

    def delete_folder_children(self, full_path: str) -> None:
        prefix = full_path.rstrip("/")
        with self._lock:
            self._dirs.pop(prefix, None)
            for d in [k for k in self._dirs if k.startswith(prefix + "/")]:
                self._dirs.pop(d, None)

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> list[Entry]:
        with self._lock:
            names = sorted(self._dirs.get(dir_path.rstrip("/") or "/", {}))
            out = []
            for name in names:
                if start_file_name:
                    if inclusive and name < start_file_name:
                        continue
                    if not inclusive and name <= start_file_name:
                        continue
                out.append(self._dirs[dir_path.rstrip("/") or "/"][name])
                if len(out) >= limit:
                    break
            return out


class SqliteFilerStore:
    """Durable store with the abstract-sql schema shape
    (dirhash+name keyed rows, ref weed/filer2/abstract_sql)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        self.write_rounds = 0  # see MemoryFilerStore.write_rounds
        self._conn.execute(
            """CREATE TABLE IF NOT EXISTS filemeta (
                directory TEXT NOT NULL,
                name TEXT NOT NULL,
                meta TEXT NOT NULL,
                PRIMARY KEY (directory, name)
            )"""
        )
        self._conn.commit()

    def insert_entry(self, entry: Entry) -> None:
        d, name = _split(entry.full_path)
        with self._lock:
            self.write_rounds += 1
            self._conn.execute(
                "REPLACE INTO filemeta (directory, name, meta) VALUES (?,?,?)",
                (d, name, json.dumps(entry.to_dict())),
            )
            self._conn.commit()

    update_entry = insert_entry

    def insert_many(self, entries: list[Entry]) -> None:
        """Batched upsert: ONE executemany + ONE commit for the whole
        batch — this is where gate coalescing buys real durability
        round-trips back (per-entry insert pays a commit each)."""
        if not entries:
            return
        rows = []
        for entry in entries:
            d, name = _split(entry.full_path)
            rows.append((d, name, json.dumps(entry.to_dict())))
        with self._lock:
            self.write_rounds += 1
            self._conn.executemany(
                "REPLACE INTO filemeta (directory, name, meta) VALUES (?,?,?)",
                rows,
            )
            self._conn.commit()

    def find_entry(self, full_path: str) -> Optional[Entry]:
        d, name = _split(full_path)
        with self._lock:
            row = self._conn.execute(
                "SELECT meta FROM filemeta WHERE directory=? AND name=?",
                (d, name),
            ).fetchone()
        return Entry.from_dict(json.loads(row[0])) if row else None

    def find_many(self, paths: list[str]) -> dict[str, Entry]:
        """ONE row-value IN query for many paths: the per-query
        prepare/step overhead amortizes over the batch, and sqlite
        releases the GIL inside the C probe — the property the sharded
        store's parallel fan-out rides."""
        out: dict[str, Entry] = {}
        if not paths:
            return out
        keys = [_split(p) for p in paths]
        by_key = {k: p for k, p in zip(keys, paths)}
        uniq = list(by_key)
        with self._lock:
            for i in range(0, len(uniq), 200):
                chunk = uniq[i : i + 200]
                placeholders = ",".join(["(?,?)"] * len(chunk))
                rows = self._conn.execute(
                    "SELECT directory, name, meta FROM filemeta "
                    f"WHERE (directory, name) IN (VALUES {placeholders})",
                    [x for pair in chunk for x in pair],
                ).fetchall()
                for d, name, meta in rows:
                    out[by_key[(d, name)]] = Entry.from_dict(
                        json.loads(meta)
                    )
        return out

    def iter_all(self):
        with self._lock:
            rows = self._conn.execute(
                "SELECT directory, name, meta FROM filemeta "
                "ORDER BY directory, name"
            ).fetchall()
        return (
            (d, name, Entry.from_dict(json.loads(meta)))
            for d, name, meta in rows
        )

    def delete_entry(self, full_path: str) -> None:
        d, name = _split(full_path)
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE directory=? AND name=?", (d, name)
            )
            self._conn.commit()

    def delete_folder_children(self, full_path: str) -> None:
        prefix = full_path.rstrip("/")
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE directory=? OR directory LIKE ?",
                (prefix, prefix + "/%"),
            )
            self._conn.commit()

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> list[Entry]:
        op = ">=" if inclusive else ">"
        with self._lock:
            rows = self._conn.execute(
                f"SELECT meta FROM filemeta WHERE directory=? AND name {op} ? "
                "ORDER BY name LIMIT ?",
                (dir_path.rstrip("/") or "/", start_file_name, limit),
            ).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def scan_directory_entries(
        self,
        dir_path: str,
        start_file_name: str,
        inclusive: bool,
        limit: int,
        upper_bound: str = "",
    ) -> list[Entry]:
        """list_directory_entries with the scan's UPPER bound pushed into
        the indexed range predicate (PR 7 follow-up): a prefix-bounded
        LIST page over this store pulls only rows inside
        [start, upper_bound), never a full generic page it then discards
        — scanned-rows-per-page matches the in-memory stores'
        O(max-keys) bound. The (directory, name) primary key makes both
        bounds one index range."""
        if not upper_bound:
            return self.list_directory_entries(
                dir_path, start_file_name, inclusive, limit
            )
        op = ">=" if inclusive else ">"
        with self._lock:
            rows = self._conn.execute(
                f"SELECT meta FROM filemeta WHERE directory=? AND name {op} ?"
                " AND name < ? ORDER BY name LIMIT ?",
                (
                    dir_path.rstrip("/") or "/",
                    start_file_name,
                    upper_bound,
                    limit,
                ),
            ).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]


# ------------- S3-key-order subtree range scan (ISSUE 7 LIST path) -------------
#
# An S3 LIST over a filer-backed bucket must produce keys in full-path
# order while the stores key entries by (directory, name). The old
# gateway walked the WHOLE bucket subtree per request and sorted; this
# scanner streams the subtree lazily in exact S3 key order — a
# directory d's subtree is contiguous at sort position d+"/" — pulling
# bounded pages per directory level, so one LIST page costs O(page),
# not O(bucket). `ScanStats.scanned` discloses the actual entries
# pulled (the bench's scanned-entries-per-request number).

_MAX_CHAR = chr(0x10FFFF)


def prefix_successor(prefix: str) -> str:
    """Smallest string greater than EVERY string with this prefix
    ('' when none exists) — the seek-past-a-delimiter-group cursor."""
    p = prefix.rstrip(_MAX_CHAR)
    if not p:
        return ""
    return p[:-1] + chr(ord(p[-1]) + 1)


class ScanStats:
    """Entries pulled from the store by a scan — the disclosed work
    bound of a LIST page."""

    __slots__ = ("scanned",)

    def __init__(self):
        self.scanned = 0


def _iter_dir_entries(
    store, dir_path: str, floor: str, stats, page: int, upper: str = ""
):
    """Entries of one directory in name order starting at `floor`
    (inclusive), streamed in `page`-sized rounds through the store's
    bounded range scan (`list_directory_entries` resumes AT the cursor
    on every store family, so each round costs O(page) regardless of
    directory size — the LSM store additionally range-filters its
    memtable source before sorting). When the caller knows the scan's
    UPPER bound (a prefix's successor) and the store can push it into
    its query (`scan_directory_entries`, the sqlite store's indexed
    range predicate), the final page pulls only in-range rows instead
    of a generic page the consumer would discard. Every PULLED entry
    counts into `stats`, whether or not the consumer keeps it: the
    disclosed scanned-entries number is store work done, not results
    returned."""
    scan = getattr(store, "scan_directory_entries", None) if upper else None
    cursor, inclusive = floor, True
    while True:
        if scan is not None:
            batch = scan(dir_path, cursor, inclusive, page, upper)
        else:
            batch = store.list_directory_entries(
                dir_path, cursor, inclusive, page
            )
        if stats is not None:
            stats.scanned += len(batch)
        for e in batch:
            yield e
        if len(batch) < page:
            return
        cursor, inclusive = batch[-1].name, False


def scan_subtree(
    store,
    root: str,
    start_at: str = "",
    prefix: str = "",
    stats: Optional[ScanStats] = None,
    page: int = 64,
    descend=None,
):
    """Yield (key, Entry) for file entries under `root` in S3 key order.

    - `key` is the "/"-joined path relative to root;
    - keys satisfy key >= start_at (inclusive lower bound) and
      key.startswith(prefix) — both pushed down into per-directory page
      cursors, so skipped ranges are never enumerated;
    - `descend(dir_key)` (dir_key ends with "/") may return False to
      SKIP a whole subtree; the scanner then yields one (dir_key, None)
      group marker at its sort position instead — the delimiter="/"
      CommonPrefixes path, which pays O(1) per group rather than
      enumerating it. The marker's key may sort below start_at when
      start_at points inside the group (S3 lists a group that still has
      keys past the marker).

    Name order within one directory is NOT key order (a directory d
    sorts at d+"/", after files like d"!"): a small look-ahead heap
    reorders entries, safe because an unread entry's sort key is always
    greater than the last name pulled.
    """
    yield from _scan_dir(
        store, root.rstrip("/"), "", start_at, prefix, stats, page, descend
    )


def _name_floor(start_at: str) -> str:
    """Lowest directory-entry NAME that can still contribute a key
    >= start_at: start_at truncated before its first char <= "/". Names
    below this can neither be files >= start_at nor directories whose
    subtree (keys name+"/"+...) reaches start_at — a dir named "0" can
    hold keys above start_at "0-x/y" because "/" outsorts "-", so naive
    first-path-component truncation would skip live subtrees."""
    for i, c in enumerate(start_at):
        if c <= "/":
            return start_at[:i]
    return start_at


def _scan_dir(store, dir_path, rel, start_at, prefix, stats, page, descend):
    import heapq

    floor = _name_floor(start_at) if start_at else ""
    stop_at = ""
    if prefix:
        pc = prefix.partition("/")[0]
        if "/" in prefix:
            # only the directory named exactly `pc` can contribute
            floor = max(floor, pc)
            stop_at = pc + "\x00"
        else:
            floor = max(floor, prefix)
            stop_at = prefix_successor(prefix)

    def emit(e):
        if e.is_directory:
            sub = e.name + "/"
            if start_at and not start_at.startswith(sub) and start_at > sub:
                return  # whole subtree sorts below start_at
            if prefix:
                if prefix.startswith(sub):
                    child_prefix = prefix[len(sub):]
                elif sub.startswith(prefix):
                    child_prefix = ""
                else:
                    return
            else:
                child_prefix = ""
            child_start = (
                start_at[len(sub):] if start_at.startswith(sub) else ""
            )
            key_prefix = rel + sub
            if descend is not None and not descend(key_prefix):
                yield (key_prefix, None)  # group marker; subtree skipped
                return
            yield from _scan_dir(
                store, e.full_path, key_prefix, child_start, child_prefix,
                stats, page, descend,
            )
        else:
            name = e.name
            if start_at and name < start_at:
                return
            if prefix and ("/" in prefix or not name.startswith(prefix)):
                return
            yield (rel + name, e)

    it = _iter_dir_entries(store, dir_path, floor, stats, page, upper=stop_at)
    heap: list = []
    seq = 0
    last = ""
    done = False
    while True:
        # pull until the heap head is provably next in sort order: any
        # unread entry's sort key exceeds the last NAME pulled
        while not done and (not heap or heap[0][0] > last):
            e = next(it, None)
            if e is None:
                done = True
                break
            name = e.name
            if stop_at and name >= stop_at:
                done = True
                break
            last = name
            heapq.heappush(
                heap, ((name + "/") if e.is_directory else name, seq, e)
            )
            seq += 1
        if not heap:
            return
        yield from emit(heapq.heappop(heap)[2])


class LogFilerStore(MemoryFilerStore):
    """Append-only log store: every mutation appends a msgpack record to a
    WAL; reads serve from the in-memory index. Open replays the log, then
    compacts it to just the live entries (the leveldb2-class durability
    role, ref weed/filer2/leveldb2, without a database dependency)."""

    def __init__(self, path: str):
        super().__init__()
        import msgpack

        self._path = path
        self._packer = msgpack.Packer(use_bin_type=True)
        # replay
        import os

        if os.path.exists(path):
            with open(path, "rb") as f:
                unpacker = msgpack.Unpacker(f, raw=False)
                for rec in unpacker:
                    op = rec.get("op")
                    if op == "put":
                        super().insert_entry(Entry.from_dict(rec["entry"]))
                    elif op == "del":
                        super().delete_entry(rec["path"])
                    elif op == "delchildren":
                        super().delete_folder_children(rec["path"])
        self._compact()
        self._f = open(path, "ab")

    def _compact(self) -> None:
        """Rewrite the log with only live entries (atomic replace)."""
        import os

        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            with self._lock:
                for d in sorted(self._dirs):
                    for name in sorted(self._dirs[d]):
                        f.write(
                            self._packer.pack(
                                {
                                    "op": "put",
                                    "entry": self._dirs[d][name].to_dict(),
                                }
                            )
                        )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def _append(self, rec: dict) -> None:
        import os

        self._f.write(self._packer.pack(rec))
        self._f.flush()
        os.fsync(self._f.fileno())  # acknowledged mutations survive a crash

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            super().insert_entry(entry)
            self._append({"op": "put", "entry": entry.to_dict()})

    update_entry = insert_entry

    def insert_many(self, entries: list[Entry]) -> None:
        """Batched upsert: one buffered write + ONE flush/fsync for the
        whole batch (the per-entry path fsyncs each record)."""
        if not entries:
            return
        with self._lock:
            self.write_rounds += 1
            for entry in entries:
                d, name = _split(entry.full_path)
                self._dirs.setdefault(d, {})[name] = entry
                self._f.write(
                    self._packer.pack(
                        {"op": "put", "entry": entry.to_dict()}
                    )
                )
            import os

            self._f.flush()
            os.fsync(self._f.fileno())

    def delete_entry(self, full_path: str) -> None:
        with self._lock:
            super().delete_entry(full_path)
            self._append({"op": "del", "path": full_path})

    def delete_folder_children(self, full_path: str) -> None:
        with self._lock:
            super().delete_folder_children(full_path)
            self._append({"op": "delchildren", "path": full_path})

    def close(self) -> None:
        self._f.close()
