"""Metadata serving fleet: shard-RANGE filer processes (ISSUE 20).

The prefix-sharded store (sharded_store.py, ISSUE 15) scales the filer
namespace across threads of ONE process; this module scales it across
PROCESSES. N filer servers each own a contiguous directory range of one
crash-safe FLEETMAP (the SHARDMAP discipline lifted to the fleet level:
shadow-write + fsync + atomic rename, versioned, epoch-stamped), and the
map itself routes clients — a `FleetRouter` picks the owner by directory,
and every server double-checks ownership on arrival, FORWARDING to the
true owner when a stale client (or a mid-move map) lands a request on the
wrong process. Zero-misroute therefore never depends on client map
freshness: the server-side hop is the authority, bounded by a hop count.

Range moves between two LIVE processes ride the delta-window discipline
the in-process rebalance proved out (ISSUE 15 REBALANCE_STEPS):

    intent  — pending_move recorded in the map (crash-recoverable)
    purge   — destination drops strays from any earlier dead attempt
    copy    — entries page to the destination UNFENCED (live traffic
              keeps mutating the range; the meta-log watermark taken
              before the copy brackets what the delta must replay)
    fence   — mutations to the moving range park on an asyncio event;
              in-flight admitted mutations DRAIN before the delta read,
              so the meta log is quiescent for the range
    delta   — meta-log events since the watermark, filtered to the
              range, replay onto the destination
    commit  — bounds + epoch flip in ONE atomic map rewrite (with the
              source's cleanup obligation recorded); the fence lifts and
              parked mutations re-route themselves to the new owner
    cleanup — the source deletes its local copy of the range

A path can never resolve to two owners: before commit every map (and
every server-side ownership check) routes the range to the source, whose
fence serializes the hand-off; after commit the source's own fresh map
forwards stragglers to the destination.

Directory SPINE entries (the ancestor placeholders `_ensure_parents`
mints) are deliberately replicated fleet-wide: the owner of a leaf's
directory creates the spine locally and broadcasts the newly created
placeholders to every peer (idempotent upserts), so `ListEntries` on any
member sees its subdirectories regardless of which member owns their
contents. File entries live on exactly one owner.
"""

from __future__ import annotations

import asyncio
import bisect
import json
import os
import time
from typing import Optional

from ..pb import grpc_address
from ..pb.rpc import Stub
from ..util import log as _log
from ..util.metrics import (
    FLEET_FORWARDED,
    FLEET_INGESTED,
    FLEET_MOVES,
)
from .entry import Entry
from .sharded_store import default_bounds

FLEET_MAP_NAME = "FLEETMAP"
MAX_HOPS = 3  # forward chain bound: client -> stale member -> owner
_INGEST_BATCH = 512


def dir_of(full_path: str) -> str:
    """Routing key of a path: its parent directory ('/' files route to
    the first member, like the sharded store's top-level band)."""
    if full_path == "/":
        return "/"
    d = full_path.rstrip("/").rsplit("/", 1)[0]
    return d or "/"


def ancestor_dirs(full_path: str) -> list[str]:
    """Every ancestor directory of a path, root-first, '/' excluded —
    the spine the owner mints locally and broadcasts fleet-wide."""
    out: list[str] = []
    d = dir_of(full_path)
    while d != "/":
        out.append(d)
        d = dir_of(d)
    out.reverse()
    return out


def in_range(directory: str, lo: str, hi: str) -> bool:
    """[lo, hi) over directory strings; '' means unbounded on that side."""
    return (not lo or directory >= lo) and (not hi or directory < hi)


class FleetMap:
    """One committed fleet routing state. Immutable by convention —
    mutations go through copy + atomic file rewrite, never in place."""

    __slots__ = (
        "version", "epoch", "addresses", "bounds",
        "pending_move", "pending_cleanup",
    )

    def __init__(
        self,
        addresses: list[str],
        bounds: Optional[list[str]] = None,
        epoch: int = 1,
        pending_move: Optional[dict] = None,
        pending_cleanup: Optional[dict] = None,
    ):
        self.version = 1
        self.addresses = list(addresses)
        self.bounds = (
            list(bounds)
            if bounds is not None
            else default_bounds(len(addresses))
        )
        if len(self.bounds) != max(len(self.addresses) - 1, 0):
            raise ValueError(
                f"fleet map: {len(self.addresses)} members need "
                f"{len(self.addresses) - 1} bounds, got {len(self.bounds)}"
            )
        self.epoch = epoch
        self.pending_move = pending_move
        self.pending_cleanup = pending_cleanup

    # ---------------- routing ----------------
    def index_for_dir(self, directory: str) -> int:
        return bisect.bisect_right(self.bounds, directory)

    def owner_for_dir(self, directory: str) -> str:
        return self.addresses[self.index_for_dir(directory)]

    def range_of(self, index: int) -> tuple[str, str]:
        """Member's [lo, hi) directory range; '' = unbounded side."""
        lo = self.bounds[index - 1] if index > 0 else ""
        hi = self.bounds[index] if index < len(self.bounds) else ""
        return lo, hi

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "epoch": self.epoch,
            "addresses": self.addresses,
            "bounds": self.bounds,
            "pending_move": self.pending_move,
            "pending_cleanup": self.pending_cleanup,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FleetMap":
        return cls(
            addresses=list(d.get("addresses", [])),
            bounds=list(d.get("bounds", [])),
            epoch=int(d.get("epoch", 1)),
            pending_move=d.get("pending_move"),
            pending_cleanup=d.get("pending_cleanup"),
        )


def write_fleet_map(path: str, fmap: FleetMap) -> None:
    """Crash-safe map rewrite: shadow-write + fsync + atomic rename —
    a reader sees the old committed map or the new one, never a torn
    file (the SHARDMAP/fid-refs discipline)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(fmap.to_dict(), f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_fleet_map(path: str) -> FleetMap:
    with open(path) as f:
        return FleetMap.from_dict(json.load(f))


class _MapCache:
    """mtime-checked map reader shared by members and routers: one stat
    per check interval, a full re-read only when the file changed."""

    def __init__(self, path: str, check_interval_s: float = 0.05):
        self.path = path
        self.check_interval_s = check_interval_s
        self._map: Optional[FleetMap] = None
        self._mtime = -1.0
        self._checked = 0.0

    def current(self, force: bool = False) -> FleetMap:
        now = time.monotonic()
        if (
            not force
            and self._map is not None
            and now - self._checked < self.check_interval_s
        ):
            return self._map
        self._checked = now
        try:
            mtime = os.stat(self.path).st_mtime_ns
        except OSError:
            if self._map is not None:
                return self._map
            raise
        if self._map is None or mtime != self._mtime:
            self._map = read_fleet_map(self.path)
            self._mtime = mtime
        return self._map


class FleetRouter:
    """Client-side routing over the fleet map: picks the owning filer
    for a path. Reads the shared map file when one is reachable (the
    single-host / ProcCluster shape); otherwise fetches the map from a
    seed member's FleetStatus RPC and caches it by epoch."""

    def __init__(self, map_path: str = "", seed: str = "", ttl_s: float = 0.25):
        if not map_path and not seed:
            raise ValueError("fleet router needs a map path or a seed filer")
        self._cache = _MapCache(map_path) if map_path else None
        self.seed = seed
        self.ttl_s = ttl_s
        self._map: Optional[FleetMap] = None
        self._fetched = 0.0

    def current(self, force: bool = False) -> FleetMap:
        if self._cache is not None:
            return self._cache.current(force=force)
        if self._map is None:
            raise RuntimeError("fleet router: call refresh() first")
        return self._map

    async def refresh(self, force: bool = False) -> FleetMap:
        if self._cache is not None:
            return self._cache.current(force=force)
        now = time.monotonic()
        if self._map is not None and not force and (
            now - self._fetched < self.ttl_s
        ):
            return self._map
        stub = Stub(grpc_address(self.seed), "filer")
        resp = await stub.call("FleetStatus", {}, timeout=10.0)
        if not resp.get("configured"):
            raise RuntimeError(f"filer {self.seed} is not in a fleet")
        self._map = FleetMap.from_dict(resp["map"])
        self._fetched = now
        return self._map

    def route_path(self, full_path: str) -> str:
        """HTTP address of the member owning this path's directory."""
        return self.current().owner_for_dir(dir_of(full_path))

    def route_dir(self, directory: str) -> str:
        return self.current().owner_for_dir(directory)


class FleetMember:
    """The server-side half: ownership checks, forwarding, the fence,
    ingest, and the range-move driver. One per fleet-mode FilerServer."""

    def __init__(self, map_path: str, self_addr: str, filer):
        self.map_path = map_path
        self.self_addr = self_addr
        self.filer = filer
        self._cache = _MapCache(map_path)
        self._fence: Optional[tuple[str, str]] = None
        self._fence_cleared: Optional[asyncio.Event] = None
        self._inflight = 0
        self._move_lock = asyncio.Lock()
        self.counters = {
            "forwarded": 0,
            "ingested": 0,
            "purged": 0,
            "spine_broadcasts": 0,
            "moves_committed": 0,
            "moves_failed": 0,
            "fence_waits": 0,
            "loop_refusals": 0,
        }

    # ---------------- map access ----------------
    def map(self, force: bool = False) -> FleetMap:
        return self._cache.current(force=force)

    def owner_for_dir(self, directory: str) -> str:
        return self.map().owner_for_dir(directory)

    def self_index(self, fmap: Optional[FleetMap] = None) -> int:
        fmap = fmap or self.map()
        return fmap.addresses.index(self.self_addr)

    # ---------------- admission ----------------
    def _fenced(self, directory: str) -> bool:
        f = self._fence
        return f is not None and in_range(directory, f[0], f[1])

    def _fence_event(self) -> asyncio.Event:
        if self._fence_cleared is None:
            self._fence_cleared = asyncio.Event()
            self._fence_cleared.set()
        return self._fence_cleared

    async def admit(
        self, method: str, req: dict, directory: str, mutation: bool = False
    ) -> Optional[dict]:
        """Ownership + fence gate for one gRPC request. Returns None to
        serve locally — with the mutation ADMITTED under the fence when
        mutation=True (the caller MUST call finish_mutation() on every
        exit path) — or the response to return (forwarded result /
        routing error)."""
        if req.get("fleet_local"):
            # broadcast/recovery traffic: serve here regardless of the
            # map, but mutations still respect the fence
            while mutation and self._fenced(directory):
                self.counters["fence_waits"] += 1
                await self._fence_event().wait()
            if mutation:
                self._inflight += 1
            return None
        while True:
            owner = self.owner_for_dir(directory)
            if owner != self.self_addr and int(req.get("fleet_hops", 0)) > 0:
                # the sender routed here on a map NEWER than our cached
                # one (a move just committed): force-refresh before
                # bouncing the request back, or two members ping-pong it
                # across the staleness window until the hop bound trips
                owner = self.map(force=True).owner_for_dir(directory)
            if owner != self.self_addr:
                return await self.forward(method, req, owner)
            if mutation and self._fenced(directory):
                # a move of this range is committing: park until the
                # fence lifts, then re-check — ownership usually flipped
                self.counters["fence_waits"] += 1
                await self._fence_event().wait()
                continue
            if mutation:
                self._inflight += 1
            return None

    def finish_mutation(self) -> None:
        self._inflight -= 1

    async def forward(self, method: str, req: dict, owner: str) -> dict:
        hops = int(req.get("fleet_hops", 0))
        if hops >= MAX_HOPS:
            self.counters["loop_refusals"] += 1
            return {"error": "fleet routing loop", "owner": owner}
        out = dict(req)
        out["fleet_hops"] = hops + 1
        self.counters["forwarded"] += 1
        FLEET_FORWARDED.inc(op=method)
        stub = Stub(grpc_address(owner), "filer")
        return await stub.call(method, out, timeout=15.0)

    # ---------------- ingest (dst side of moves + spine broadcast) ----------------
    def ingest(self, req: dict) -> dict:
        """Direct store application: range purges, entry pages, and
        delta deletes land on the LOCAL store without touching the
        Filer (no meta-log events, no chunk frees — the bytes already
        live on this cluster and the move must not look like churn to
        this member's subscribers)."""
        store = self.filer.store
        out: dict = {}
        if "purge_lo" in req:
            n = self._delete_range_local(
                req["purge_lo"], req.get("purge_hi", "")
            )
            self.counters["purged"] += n
            out["purged"] = n
        entries = [Entry.from_dict(d) for d in req.get("entries", [])]
        if entries:
            im = getattr(store, "insert_many", None)
            if im is not None:
                im(entries)
            else:
                for e in entries:
                    store.insert_entry(e)
            self.counters["ingested"] += len(entries)
            FLEET_INGESTED.inc(len(entries))
            out["ingested"] = len(entries)
        deletes = req.get("deletes", [])
        for path in deletes:
            store.delete_folder_children(path)
            store.delete_entry(path)
        if deletes:
            out["deleted"] = len(deletes)
        return out

    async def broadcast_spine(self, entries: list[Entry]) -> None:
        """Replicate freshly minted directory placeholders to every
        other member (idempotent upserts) so any member's ListEntries
        sees its subdirectories. Awaited by the create that minted them
        — a successful create implies a visible spine fleet-wide."""
        if not entries:
            return
        fmap = self.map()
        peers = [a for a in fmap.addresses if a != self.self_addr]
        if not peers:
            return
        body = {
            "entries": [e.to_dict() for e in entries],
            "fleet_local": True,
        }
        self.counters["spine_broadcasts"] += 1

        async def one(addr: str):
            stub = Stub(grpc_address(addr), "filer")
            await stub.call("FleetIngest", body, timeout=10.0)

        results = await asyncio.gather(
            *(one(a) for a in peers), return_exceptions=True
        )
        for addr, r in zip(peers, results):
            if isinstance(r, BaseException):
                # a dead peer misses placeholders, not data: its next
                # restart re-reads the map and serves what it owns; the
                # spine self-heals on the next create under that branch
                _log.warning(
                    "fleet spine broadcast to %s failed: %s", addr, r
                )

    async def broadcast(self, method: str, req: dict) -> list[dict]:
        """Send one request to EVERY other member (recursive directory
        delete / directory rename: each member applies its local slice)."""
        fmap = self.map()
        peers = [a for a in fmap.addresses if a != self.self_addr]
        out = dict(req)
        out["fleet_local"] = True

        async def one(addr: str) -> dict:
            stub = Stub(grpc_address(addr), "filer")
            return await stub.call(method, out, timeout=15.0)

        results = await asyncio.gather(
            *(one(a) for a in peers), return_exceptions=True
        )
        resp = []
        for addr, r in zip(peers, results):
            if isinstance(r, BaseException):
                resp.append({"error": str(r), "member": addr})
            else:
                resp.append(r)
        return resp

    # ---------------- local range helpers ----------------
    def _collect_range(self, lo: str, hi: str) -> list[Entry]:
        return [
            e
            for d, _name, e in self.filer.store.iter_all()
            if in_range(d, lo, hi)
        ]

    def _delete_range_local(self, lo: str, hi: str) -> int:
        store = self.filer.store
        doomed = [
            e.full_path
            for d, _name, e in store.iter_all()
            if in_range(d, lo, hi)
        ]
        for path in doomed:
            store.delete_entry(path)
        return len(doomed)

    # ---------------- the range move (runs on the SOURCE) ----------------
    async def move_range(self, dst: str, lo: str, hi: str) -> dict:
        """Move [lo, hi) to the ADJACENT member `dst` under live traffic
        (see the module docstring's step ladder). Serialized per member;
        raises ValueError on a malformed move request."""
        async with self._move_lock:
            fmap = self.map(force=True)
            si = self.self_index(fmap)
            try:
                di = fmap.addresses.index(dst)
            except ValueError:
                raise ValueError(f"fleet move: {dst!r} is not a member")
            if abs(di - si) != 1:
                raise ValueError(
                    "fleet move: ranges move between ADJACENT members "
                    f"(self at {si}, dst at {di})"
                )
            my_lo, my_hi = fmap.range_of(si)
            if not lo or not hi or lo >= hi:
                raise ValueError(f"fleet move: bad range [{lo!r}, {hi!r})")
            if di == si + 1:
                # give our TAIL to the right neighbor
                if hi != my_hi or not in_range(lo, my_lo, my_hi):
                    raise ValueError(
                        f"fleet move right needs [split, {my_hi!r}), got "
                        f"[{lo!r}, {hi!r})"
                    )
            else:
                # give our HEAD to the left neighbor
                if lo != my_lo or not in_range(hi, my_lo, my_hi):
                    raise ValueError(
                        f"fleet move left needs [{my_lo!r}, split), got "
                        f"[{lo!r}, {hi!r})"
                    )
            try:
                return await self._run_move(fmap, si, di, dst, lo, hi)
            except Exception:
                self.counters["moves_failed"] += 1
                FLEET_MOVES.inc(outcome="failed")
                raise

    async def _run_move(
        self, fmap: FleetMap, si: int, di: int, dst: str, lo: str, hi: str
    ) -> dict:
        loop = asyncio.get_event_loop()
        t0 = time.perf_counter()
        # intent: crash-recoverable before any copy lands on dst
        intent = FleetMap(
            fmap.addresses, fmap.bounds, fmap.epoch,
            pending_move={"src": self.self_addr, "dst": dst,
                          "lo": lo, "hi": hi},
            pending_cleanup=fmap.pending_cleanup,
        )
        write_fleet_map(self.map_path, intent)
        ts0 = self.filer.meta_log.last_ts_ns
        dst_stub = Stub(grpc_address(dst), "filer")
        # purge: strays from an earlier dead attempt would shadow the
        # delta's deletes
        await dst_stub.call(
            "FleetIngest",
            {"purge_lo": lo, "purge_hi": hi, "fleet_local": True},
            timeout=30.0,
        )
        # copy (unfenced: live traffic keeps landing; the delta replays it)
        entries = await loop.run_in_executor(
            None, self._collect_range, lo, hi
        )
        copied = len(entries)
        for i in range(0, len(entries), _INGEST_BATCH):
            batch = entries[i : i + _INGEST_BATCH]
            await dst_stub.call(
                "FleetIngest",
                {"entries": [e.to_dict() for e in batch],
                 "fleet_local": True},
                timeout=30.0,
            )
        # fence + drain: park new mutations to the range, let admitted
        # ones finish, so the meta log is quiescent for [lo, hi)
        self._fence = (lo, hi)
        self._fence_event().clear()
        delta_ups = delta_dels = 0
        try:
            waited = 0.0
            while self._inflight > 0:
                await asyncio.sleep(0.005)
                waited += 0.005
                if waited > 10.0:
                    raise TimeoutError(
                        "fleet move: admitted mutations did not drain"
                    )
            events, _wm = self.filer.meta_log.read_since_with_watermark(
                ts0
            )
            ups: dict[str, dict] = {}
            dels: dict[str, bool] = {}
            for ev in events:
                new = ev.new_entry
                old = ev.old_entry
                if new is not None and in_range(
                    dir_of(new["full_path"]), lo, hi
                ):
                    dels.pop(new["full_path"], None)
                    ups[new["full_path"]] = new
                if ev.event_type in ("delete", "rename") and old is not None:
                    op = old["full_path"]
                    if in_range(dir_of(op), lo, hi) and (
                        new is None or new["full_path"] != op
                    ):
                        ups.pop(op, None)
                        dels[op] = True
            delta_ups, delta_dels = len(ups), len(dels)
            if ups or dels:
                await dst_stub.call(
                    "FleetIngest",
                    {"entries": list(ups.values()),
                     "deletes": list(dels),
                     "fleet_local": True},
                    timeout=30.0,
                )
            # commit: bounds + epoch flip atomically; the source's
            # cleanup obligation rides the same write
            bounds = list(fmap.bounds)
            if di == si + 1:
                bounds[si] = lo
            else:
                bounds[si - 1] = hi
            committed = FleetMap(
                fmap.addresses, bounds, fmap.epoch + 1,
                pending_move=None,
                pending_cleanup={"src": self.self_addr, "lo": lo, "hi": hi},
            )
            write_fleet_map(self.map_path, committed)
            self._cache.current(force=True)
        except Exception:
            # abort: dst never owned the range (bounds unchanged), its
            # strays are purged by the next attempt's purge step
            aborted = FleetMap(
                fmap.addresses, fmap.bounds, fmap.epoch,
                pending_move=None, pending_cleanup=fmap.pending_cleanup,
            )
            write_fleet_map(self.map_path, aborted)
            self._cache.current(force=True)
            raise
        finally:
            self._fence = None
            self._fence_event().set()
        # cleanup: our copy of the range is dead weight now; stragglers
        # routed here forward to dst off our own fresh map
        await loop.run_in_executor(None, self._delete_range_local, lo, hi)
        done = self.map(force=True)
        if (
            done.pending_cleanup
            and done.pending_cleanup.get("src") == self.self_addr
        ):
            write_fleet_map(
                self.map_path,
                FleetMap(
                    done.addresses, done.bounds, done.epoch,
                    pending_move=done.pending_move, pending_cleanup=None,
                ),
            )
            self._cache.current(force=True)
        self.counters["moves_committed"] += 1
        FLEET_MOVES.inc(outcome="committed")
        return {
            "copied": copied,
            "delta_upserts": delta_ups,
            "delta_deletes": delta_dels,
            "epoch": fmap.epoch + 1,
            "wall_s": round(time.perf_counter() - t0, 4),
        }

    # ---------------- crash recovery (before serving) ----------------
    def recover(self) -> dict:
        """Finish or roll back whatever a crash left in the map. The
        DESTINATION of an uncommitted move purges its strays (the
        committed map never routed the range to it); the SOURCE clears
        a dangling intent and finishes any committed-but-uncleaned
        local range delete."""
        out = {"purged": 0, "cleaned": 0, "intent_cleared": False}
        try:
            fmap = self.map(force=True)
        except OSError:
            return out
        pm = fmap.pending_move
        if pm and pm.get("dst") == self.self_addr:
            out["purged"] = self._delete_range_local(pm["lo"], pm["hi"])
        if pm and pm.get("src") == self.self_addr:
            write_fleet_map(
                self.map_path,
                FleetMap(
                    fmap.addresses, fmap.bounds, fmap.epoch,
                    pending_move=None,
                    pending_cleanup=fmap.pending_cleanup,
                ),
            )
            out["intent_cleared"] = True
            fmap = self.map(force=True)
        pc = fmap.pending_cleanup
        if pc and pc.get("src") == self.self_addr:
            out["cleaned"] = self._delete_range_local(pc["lo"], pc["hi"])
            write_fleet_map(
                self.map_path,
                FleetMap(
                    fmap.addresses, fmap.bounds, fmap.epoch,
                    pending_move=fmap.pending_move, pending_cleanup=None,
                ),
            )
            self._cache.current(force=True)
        return out

    def status(self) -> dict:
        fmap = self.map()
        return {
            "self": self.self_addr,
            "epoch": fmap.epoch,
            "members": len(fmap.addresses),
            "map": fmap.to_dict(),
            "range": list(fmap.range_of(self.self_index(fmap))),
            "fence": list(self._fence) if self._fence else None,
            "inflight_mutations": self._inflight,
            "counters": dict(self.counters),
        }
