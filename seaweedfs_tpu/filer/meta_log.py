"""Filer metadata change log + subscriptions.

Host-side equivalent of the reference's in-memory meta log
(ref: weed/util/log_buffer/log_buffer.go, weed/filer2/filer_notify.go,
served by the filer's SubscribeMetadata stream, filer.proto:49-53):
every namespace mutation appends an event; subscribers replay from a
starting timestamp and then follow live, filtered by path prefix.

The buffer is a bounded ring — subscribers that fall further behind than
the ring capacity miss events (the reference's LogBuffer similarly only
keeps a time window in memory; durable history rides the notification
sinks / filer log files, not this buffer).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import AsyncIterator, Optional


class MetaLogEvent:
    __slots__ = ("ts_ns", "directory", "event_type", "old_entry", "new_entry")

    def __init__(self, ts_ns, directory, event_type, old_entry, new_entry):
        self.ts_ns = ts_ns
        self.directory = directory
        self.event_type = event_type
        self.old_entry = old_entry  # dict | None
        self.new_entry = new_entry  # dict | None

    def to_dict(self) -> dict:
        return {
            "ts_ns": self.ts_ns,
            "directory": self.directory,
            "event_notification": {
                "event_type": self.event_type,
                "old_entry": self.old_entry,
                "new_entry": self.new_entry,
            },
        }


class MetaLog:
    def __init__(self, capacity: int = 10000):
        # ts-ordered parallel lists; bisect on _ts makes read_since
        # O(log n + matches) instead of a full scan per subscriber poll
        self._events: list[MetaLogEvent] = []
        self._ts: list[int] = []
        self._capacity = capacity
        self._lock = threading.Lock()
        self._last_ts_ns = 0

    @property
    def last_ts_ns(self) -> int:
        return self._last_ts_ns

    def append(
        self,
        directory: str,
        event_type: str,
        old_entry: Optional[dict],
        new_entry: Optional[dict],
    ) -> MetaLogEvent:
        with self._lock:
            # strictly monotonic so since_ns resumption never duplicates
            ts = max(time.time_ns(), self._last_ts_ns + 1)
            self._last_ts_ns = ts
            ev = MetaLogEvent(ts, directory, event_type, old_entry, new_entry)
            self._events.append(ev)
            self._ts.append(ts)
            if len(self._events) > self._capacity * 2:
                del self._events[: -self._capacity]
                del self._ts[: -self._capacity]
            return ev

    def read_since(
        self, since_ns: int, path_prefix: str = "/"
    ) -> list[MetaLogEvent]:
        return self.read_since_with_watermark(since_ns, path_prefix)[0]

    def read_since_with_watermark(
        self, since_ns: int, path_prefix: str = "/"
    ) -> tuple[list[MetaLogEvent], int]:
        """-> (matching events, ts scanned through). The watermark is taken
        under the same lock as the slice, so resuming from it never skips
        events appended concurrently."""
        with self._lock:
            lo = bisect.bisect_right(self._ts, since_ns)
            tail = self._events[max(lo, len(self._events) - self._capacity):]
            watermark = self._last_ts_ns
        return [ev for ev in tail if _match_prefix(ev, path_prefix)], watermark

    async def subscribe(
        self,
        since_ns: int = 0,
        path_prefix: str = "/",
        poll_interval: float = 0.05,
        stopped=None,
    ) -> AsyncIterator[MetaLogEvent]:
        """Replay history after since_ns, then follow live
        (ref filer_grpc_server_sub_meta.go SubscribeMetadata loop)."""
        import asyncio

        cursor = since_ns
        while stopped is None or not stopped():
            # O(1) idle check: nothing appended since our cursor
            if self._last_ts_ns <= cursor:
                await asyncio.sleep(poll_interval)
                continue
            batch, watermark = self.read_since_with_watermark(
                cursor, path_prefix
            )
            cursor = max(cursor, watermark)
            for ev in batch:
                yield ev
            if not batch:
                await asyncio.sleep(poll_interval)


def _match_prefix(ev: MetaLogEvent, path_prefix: str) -> bool:
    if not path_prefix or path_prefix == "/":
        return True
    for entry in (ev.new_entry, ev.old_entry):
        if entry:
            full = entry.get("full_path") or (
                f"{ev.directory.rstrip('/')}/{entry.get('name', '')}"
            )
            if full.startswith(path_prefix):
                return True
    return ev.directory.startswith(path_prefix)
