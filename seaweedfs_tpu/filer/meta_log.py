"""Filer metadata change log + subscriptions.

Host-side equivalent of the reference's in-memory meta log
(ref: weed/util/log_buffer/log_buffer.go, weed/filer2/filer_notify.go,
served by the filer's SubscribeMetadata stream, filer.proto:49-53):
every namespace mutation appends an event; subscribers replay from a
starting timestamp and then follow live, filtered by path prefix.

The buffer is a bounded ring — subscribers that fall further behind than
the ring capacity miss events (the reference's LogBuffer similarly only
keeps a time window in memory; durable history rides the notification
sinks / filer log files, not this buffer).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import AsyncIterator, Optional


class MetaLogTrimmed(RuntimeError):
    """Events in (since_ns, trimmed_through] can never be delivered —
    either the subscriber's resume cursor is older than retention, or a
    sealed segment in that range is unreadable (corruption). Raised
    instead of silently resuming past the hole; the subscriber decides
    (rebuild its derived state, re-anchor past `trimmed_through`,
    alert)."""

    def __init__(self, since_ns: int, trimmed_through: int):
        super().__init__(
            f"meta-log history unavailable through {trimmed_through}; "
            f"cannot resume exactly from {since_ns}"
        )
        self.since_ns = since_ns
        self.trimmed_through = trimmed_through


class MetaLogEvent:
    __slots__ = ("ts_ns", "directory", "event_type", "old_entry", "new_entry")

    def __init__(self, ts_ns, directory, event_type, old_entry, new_entry):
        self.ts_ns = ts_ns
        self.directory = directory
        self.event_type = event_type
        self.old_entry = old_entry  # dict | None
        self.new_entry = new_entry  # dict | None

    def to_dict(self) -> dict:
        return {
            "ts_ns": self.ts_ns,
            "directory": self.directory,
            "event_notification": {
                "event_type": self.event_type,
                "old_entry": self.old_entry,
                "new_entry": self.new_entry,
            },
        }


class MetaLog:
    def __init__(self, capacity: int = 10000):
        # ts-ordered parallel lists; bisect on _ts makes read_since
        # O(log n + matches) instead of a full scan per subscriber poll
        self._events: list[MetaLogEvent] = []
        self._ts: list[int] = []
        self._capacity = capacity
        self._lock = threading.Lock()
        self._last_ts_ns = 0

    @property
    def last_ts_ns(self) -> int:
        return self._last_ts_ns

    def append(
        self,
        directory: str,
        event_type: str,
        old_entry: Optional[dict],
        new_entry: Optional[dict],
    ) -> MetaLogEvent:
        with self._lock:
            # strictly monotonic so since_ns resumption never duplicates
            ts = max(time.time_ns(), self._last_ts_ns + 1)
            self._last_ts_ns = ts
            ev = MetaLogEvent(ts, directory, event_type, old_entry, new_entry)
            self._events.append(ev)
            self._ts.append(ts)
            if len(self._events) > self._capacity * 2:
                del self._events[: -self._capacity]
                del self._ts[: -self._capacity]
            return ev

    def read_since(
        self, since_ns: int, path_prefix: str = "/"
    ) -> list[MetaLogEvent]:
        return self.read_since_with_watermark(since_ns, path_prefix)[0]

    def read_since_with_watermark(
        self, since_ns: int, path_prefix: str = "/"
    ) -> tuple[list[MetaLogEvent], int]:
        """-> (matching events, ts scanned through). The watermark is taken
        under the same lock as the slice, so resuming from it never skips
        events appended concurrently."""
        with self._lock:
            lo = bisect.bisect_right(self._ts, since_ns)
            tail = self._events[max(lo, len(self._events) - self._capacity):]
            watermark = self._last_ts_ns
        return [ev for ev in tail if _match_prefix(ev, path_prefix)], watermark

    async def subscribe(
        self,
        since_ns: int = 0,
        path_prefix: str = "/",
        poll_interval: float = 0.05,
        stopped=None,
    ) -> AsyncIterator[MetaLogEvent]:
        """Replay history after since_ns, then follow live
        (ref filer_grpc_server_sub_meta.go SubscribeMetadata loop)."""
        import asyncio

        cursor = since_ns
        while stopped is None or not stopped():
            # O(1) idle check: nothing appended since our cursor
            if self._last_ts_ns <= cursor:
                await asyncio.sleep(poll_interval)
                continue
            batch, watermark = self.read_since_with_watermark(
                cursor, path_prefix
            )
            cursor = max(cursor, watermark)
            for ev in batch:
                yield ev
            if not batch:
                await asyncio.sleep(poll_interval)


class DurableMetaLog(MetaLog):
    """MetaLog promoted from a bounded in-memory ring to a segmented
    on-disk log with resumable per-subscriber cursors (ISSUE 15).

    Layout: a directory of ``seg-<seq>.mlog`` files (msgpack record
    stream, `segment_events` records each), plus ``cursors.json``
    holding per-subscriber resume timestamps (shadow-write + atomic
    rename, the shard-map discipline). Appends go to disk FIRST (write
    + flush; fsync behind ``SEAWEEDFS_TPU_META_FEED_FSYNC`` — the
    store, not the feed, is the namespace durability authority), then
    into the inherited in-memory ring, which stays the fast tail for
    caught-up subscribers; a subscriber that fell behind the ring —
    or resumes in a fresh process — replays from the segments with the
    SAME exact-resumption guarantee the ring gives (strictly monotonic
    ts, watermark taken at the scan frontier), in bounded chunks.

    Retention is ``max_segments`` sealed segments; trimming records
    ``trimmed_through``, and a read whose resume cursor falls below it
    raises :class:`MetaLogTrimmed` — a subscriber older than retention
    is an ERROR, never silently incomplete (cursor 0 is exempt: it is
    the explicit "replay whatever history is retained" request of a
    fresh subscriber, not a resume point). Torn tails (crash mid-
    append) are truncated at open — a partial record can never be
    replayed as an event.
    """

    def __init__(
        self,
        directory: str,
        capacity: int = 10000,
        segment_events: int = 4096,
        max_segments: int = 64,
        fsync: Optional[bool] = None,
    ):
        import msgpack
        import os

        super().__init__(capacity=capacity)
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.segment_events = max(16, segment_events)
        self.max_segments = max(2, max_segments)
        if fsync is None:
            fsync = (
                os.environ.get("SEAWEEDFS_TPU_META_FEED_FSYNC", "0") or "0"
            ) != "0"
        self.fsync = fsync
        self._packer = msgpack.Packer(use_bin_type=True)
        self.trimmed_through = 0  # ts through which history was dropped
        # cursors are independent of ring/segment state: their own lock
        # keeps the synchronous cursors.json rewrite in cursor_ack from
        # blocking every append (= every namespace mutation) behind
        # file-system I/O
        self._cursor_lock = threading.Lock()
        self._cursors: Optional[dict] = None
        # events at ts <= _mem_floor may be missing from the in-memory
        # ring — reads from at/below it go to the segments
        self._segments: list[dict] = []  # {seq, path, first, last, count}
        self._scan_segments()
        self._mem_floor = self._last_ts_ns
        if self._segments:
            active = self._segments[-1]
            self._active_f = open(active["path"], "ab")
        else:
            self._open_segment(1)
        self._publish_segment_gauge()

    # ---------------- segment plumbing ----------------
    def _seg_path(self, seq: int) -> str:
        import os

        return os.path.join(self.dir, f"seg-{seq}.mlog")

    def _scan_segments(self) -> None:
        import os

        seqs = sorted(
            int(fn[4:-5])
            for fn in os.listdir(self.dir)
            if fn.startswith("seg-") and fn.endswith(".mlog")
        )
        # the trim frontier survives restarts: the TRIM marker (written
        # at each trim) is exact; without one, a seq gap at the FRONT
        # still proves retention trimmed history in a previous process
        # life (sealed segments are never empty, so only trimming
        # removes the oldest) and we reconstruct an upper bound
        marker = self._load_trim_marker()
        if marker is not None:
            self.trimmed_through = marker
        elif seqs and seqs[0] > 1:
            self.trimmed_through = -1  # fixed up after the scan below
        last_ts = 0
        for seq in seqs:
            path = self._seg_path(seq)
            first, last, count, good = self._scan_one(path)
            if count == 0 and seq != seqs[-1]:
                # empty mid-stack segment: drop it
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            # torn tail (crash mid-append): truncate to the valid prefix
            if good < os.path.getsize(path):
                with open(path, "ab") as f:
                    f.truncate(good)
            self._segments.append(
                {"seq": seq, "path": path, "first": first, "last": last,
                 "count": count}
            )
            last_ts = max(last_ts, last)
        self._last_ts_ns = last_ts
        if self.trimmed_through < 0:
            # no marker (legacy dir, or the marker file was removed):
            # bound the gap by the first retained event. This may
            # over-claim by up to one inter-segment gap (a cursor
            # between the true trim frontier and first_ts-1 raises
            # spuriously — recovery is a harmless cache drop / resume,
            # never data loss). With nothing retained at all there is
            # no bound: degrade to 0 (fresh-log behavior) rather than
            # a sentinel no follower could ever resume past.
            first_ts = next(
                (s["first"] for s in self._segments if s["count"]), 0
            )
            self.trimmed_through = max(0, first_ts - 1)

    @staticmethod
    def _scan_one(path: str) -> tuple[int, int, int, int]:
        """-> (first_ts, last_ts, count, good_bytes)."""
        import msgpack

        first = last = count = 0
        good = 0
        with open(path, "rb") as f:
            unpacker = msgpack.Unpacker(f, raw=False)
            while True:
                try:
                    rec = unpacker.unpack()
                except msgpack.OutOfData:
                    break
                except Exception:
                    break  # torn/garbage tail: keep the valid prefix
                if not isinstance(rec, dict) or "t" not in rec:
                    break
                ts = int(rec["t"])
                if count == 0:
                    first = ts
                last = ts
                count += 1
                good = unpacker.tell()  # bytes consumed by valid records
        return first, last, count, good

    def _open_segment(self, seq: int) -> None:
        self._segments.append(
            {"seq": seq, "path": self._seg_path(seq), "first": 0,
             "last": 0, "count": 0}
        )
        self._active_f = open(self._seg_path(seq), "ab")

    def _trim_marker_path(self) -> str:
        import os

        return os.path.join(self.dir, "TRIM")

    def _load_trim_marker(self) -> Optional[int]:
        import json

        try:
            with open(self._trim_marker_path()) as f:
                return int(json.load(f)["trimmed_through"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _save_trim_marker(self, value: int) -> bool:
        """Persist the exact trim frontier (tmp + atomic rename).
        Returns False on failure — the caller then SKIPS the trim, so
        the marker can over-claim (crash between save and removal:
        segments still readable) but never under-claim (a stale marker
        silently skipping trimmed events after restart)."""
        import json
        import os

        try:
            tmp = self._trim_marker_path() + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"trimmed_through": value}, f)
            os.replace(tmp, self._trim_marker_path())
            return True
        except OSError:
            return False

    def _rotate_locked(self) -> None:
        import os

        self._active_f.flush()
        os.fsync(self._active_f.fileno())  # sealed segments are durable
        self._active_f.close()
        self._open_segment(self._segments[-1]["seq"] + 1)
        if len(self._segments) > self.max_segments:
            doomed = self._segments[: len(self._segments) - self.max_segments]
            new_tt = max(
                [self.trimmed_through] + [s["last"] for s in doomed]
            )
            # marker BEFORE removal: if the frontier cannot be made
            # durable, keep the segments (retention overruns a little;
            # data kept is always safe, data silently lost never is)
            if self._save_trim_marker(new_tt):
                self.trimmed_through = new_tt
                del self._segments[: len(doomed)]
                for s in doomed:
                    try:
                        os.remove(s["path"])
                    except OSError:
                        pass
        self._publish_segment_gauge()

    def _publish_segment_gauge(self) -> None:
        try:
            from ..util.metrics import META_FEED_SEGMENTS
        except ImportError:
            return
        META_FEED_SEGMENTS.set(len(self._segments))

    # ---------------- append ----------------
    def append(self, directory, event_type, old_entry, new_entry):
        import os

        with self._lock:
            ts = max(time.time_ns(), self._last_ts_ns + 1)
            self._last_ts_ns = ts
            ev = MetaLogEvent(ts, directory, event_type, old_entry, new_entry)
            self._active_f.write(
                self._packer.pack(
                    {"t": ts, "d": directory, "e": event_type,
                     "o": old_entry, "n": new_entry}
                )
            )
            self._active_f.flush()
            if self.fsync:
                os.fsync(self._active_f.fileno())
            seg = self._segments[-1]
            if seg["count"] == 0:
                seg["first"] = ts
            seg["last"] = ts
            seg["count"] += 1
            self._events.append(ev)
            self._ts.append(ts)
            if len(self._events) > self._capacity * 2:
                # ring truncation: everything at/below the last dropped
                # event's ts now lives only in the segments
                self._mem_floor = self._ts[-self._capacity - 1]
                del self._events[: -self._capacity]
                del self._ts[: -self._capacity]
            if seg["count"] >= self.segment_events:
                self._rotate_locked()
        try:
            from ..util.metrics import META_FEED_EVENTS

            META_FEED_EVENTS.inc()
        except ImportError:
            pass
        return ev

    # ---------------- reads ----------------
    def read_since_with_watermark(
        self,
        since_ns: int,
        path_prefix: str = "/",
        limit: Optional[int] = None,
    ) -> tuple[list[MetaLogEvent], int]:
        """Exact resumption across the ring/segment boundary: when the
        cursor still falls inside the in-memory tail, this is the base
        ring read; otherwise events come off the segments in ts order.
        With `limit`, the returned watermark is the ts scanned THROUGH
        (the last examined event), so resuming from it never skips —
        a far-behind subscriber catches up in bounded chunks.

        Raises :class:`MetaLogTrimmed` when a non-zero cursor is older
        than retention (see class doc)."""
        with self._lock:
            if 0 < since_ns < self.trimmed_through:
                raise MetaLogTrimmed(since_ns, self.trimmed_through)
            # the ring SERVES only its last `capacity` events (storage
            # runs to 2x between truncations) — the served floor is the
            # newest event the ring cannot produce
            if len(self._ts) > self._capacity:
                floor = self._ts[-self._capacity - 1]
            else:
                floor = self._mem_floor
            if since_ns >= floor:
                events, wm = self._ring_read(since_ns, path_prefix)
                if limit is not None and len(events) > limit:
                    events = events[:limit]
                    wm = events[-1].ts_ns
                return events, wm
            segs = [
                dict(s) for s in self._segments if s["last"] > since_ns
            ]
            watermark = self._last_ts_ns
        out: list[MetaLogEvent] = []
        scanned_through = since_ns
        for seg in segs:
            seg_scanned = 0  # highest ts actually read from this file
            try:
                for ev in self._read_segment(seg["path"]):
                    seg_scanned = ev.ts_ns
                    if ev.ts_ns <= since_ns:
                        continue
                    scanned_through = ev.ts_ns
                    if _match_prefix(ev, path_prefix):
                        out.append(ev)
                        if limit is not None and len(out) >= limit:
                            return out, scanned_through
            except FileNotFoundError:
                # vanished segment: a retention trim raced this unlocked
                # scan — TRANSIENT. Events in the hole were not
                # delivered, so the head watermark must not become the
                # cursor; resume authority is the last ts actually
                # scanned, and the retry (now seeing the trim in
                # trimmed_through) surfaces MetaLogTrimmed
                return out, scanned_through
            if seg_scanned < seg["last"]:
                # the file EXISTS but decodes short of what was durably
                # written: corruption, which no retry will heal. Deliver
                # the healthy prefix first (a follower must not lose the
                # readable history BEFORE the hole); once the cursor sits
                # at the wall and no progress is possible, surface the
                # undeliverable range instead of re-scanning forever
                if scanned_through > since_ns:
                    return out, scanned_through
                raise MetaLogTrimmed(since_ns, seg["last"])
        # the unlocked file scan may have read events appended AFTER the
        # watermark was captured — returning the stale watermark would
        # rewind the cursor below an already-delivered event (duplicate
        # delivery); the scan frontier is the resume authority
        return out, max(watermark, scanned_through)

    def _ring_read(self, since_ns, path_prefix):
        import bisect as _bisect

        lo = _bisect.bisect_right(self._ts, since_ns)
        tail = self._events[max(lo, len(self._events) - self._capacity):]
        return (
            [ev for ev in tail if _match_prefix(ev, path_prefix)],
            self._last_ts_ns,
        )

    @staticmethod
    def _read_segment(path: str):
        """Yield the valid prefix of one segment file. A missing file
        raises FileNotFoundError (the caller distinguishes a trim race
        from corruption); any decode trouble ends the stream early —
        the caller detects the shortfall against the segment's durable
        last-ts."""
        import msgpack

        with open(path, "rb") as f:  # FileNotFoundError propagates
            try:
                for rec in msgpack.Unpacker(f, raw=False):
                    if not isinstance(rec, dict) or "t" not in rec:
                        break
                    yield MetaLogEvent(
                        int(rec["t"]), rec.get("d", ""), rec.get("e", ""),
                        rec.get("o"), rec.get("n"),
                    )
            except Exception:
                return  # torn tail: the valid prefix was already yielded

    async def subscribe(
        self,
        since_ns: int = 0,
        path_prefix: str = "/",
        poll_interval: float = 0.05,
        stopped=None,
    ) -> AsyncIterator[MetaLogEvent]:
        """Replay durable history after since_ns in bounded chunks,
        then follow live (the base loop with a chunked disk read)."""
        import asyncio

        cursor = since_ns
        while stopped is None or not stopped():
            if self._last_ts_ns <= cursor:
                await asyncio.sleep(poll_interval)
                continue
            batch, watermark = self.read_since_with_watermark(
                cursor, path_prefix, limit=1024
            )
            cursor = max(cursor, watermark)
            for ev in batch:
                yield ev
            if not batch:
                await asyncio.sleep(poll_interval)

    # ---------------- per-subscriber cursors ----------------
    def _cursor_path(self) -> str:
        import os

        return os.path.join(self.dir, "cursors.json")

    def _load_cursors(self) -> dict:
        import json

        if self._cursors is None:
            try:
                with open(self._cursor_path()) as f:
                    self._cursors = {
                        str(k): int(v) for k, v in json.load(f).items()
                    }
            except (OSError, ValueError):
                self._cursors = {}
        return self._cursors

    def cursor_load(self, name: str) -> Optional[int]:
        """Resume point for a named subscriber, or None when unknown."""
        with self._cursor_lock:
            return self._load_cursors().get(name)

    def cursor_ack(self, name: str, ts_ns: int) -> None:
        """Record that `name` has processed through ts_ns (monotonic:
        an older ack never rewinds the cursor). Shadow-write + atomic
        rename — a crash mid-ack leaves the previous cursor, and
        resuming from it re-delivers only events whose effects are
        idempotent for a correctly written subscriber."""
        import json
        import os

        with self._cursor_lock:
            cur = self._load_cursors()
            if cur.get(name, -1) >= ts_ns:
                return
            cur[name] = int(ts_ns)
            tmp = self._cursor_path() + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(cur, f)
                os.replace(tmp, self._cursor_path())
            except OSError:
                pass  # cursor persistence is best-effort per ack

    def close(self) -> None:
        import os

        with self._lock:
            try:
                self._active_f.flush()
                os.fsync(self._active_f.fileno())
                self._active_f.close()
            except OSError:
                pass


def _match_prefix(ev: MetaLogEvent, path_prefix: str) -> bool:
    if not path_prefix or path_prefix == "/":
        return True
    for entry in (ev.new_entry, ev.old_entry):
        if entry:
            full = entry.get("full_path") or (
                f"{ev.directory.rstrip('/')}/{entry.get('name', '')}"
            )
            if full.startswith(path_prefix):
                return True
    return ev.directory.startswith(path_prefix)
