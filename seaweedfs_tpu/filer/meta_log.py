"""Filer metadata change log + subscriptions.

Host-side equivalent of the reference's in-memory meta log
(ref: weed/util/log_buffer/log_buffer.go, weed/filer2/filer_notify.go,
served by the filer's SubscribeMetadata stream, filer.proto:49-53):
every namespace mutation appends an event; subscribers replay from a
starting timestamp and then follow live, filtered by path prefix.

The buffer is a bounded ring — subscribers that fall further behind than
the ring capacity miss events (the reference's LogBuffer similarly only
keeps a time window in memory; durable history rides the notification
sinks / filer log files, not this buffer).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import AsyncIterator, Optional


class MetaLogEvent:
    __slots__ = ("ts_ns", "directory", "event_type", "old_entry", "new_entry")

    def __init__(self, ts_ns, directory, event_type, old_entry, new_entry):
        self.ts_ns = ts_ns
        self.directory = directory
        self.event_type = event_type
        self.old_entry = old_entry  # dict | None
        self.new_entry = new_entry  # dict | None

    def to_dict(self) -> dict:
        return {
            "ts_ns": self.ts_ns,
            "directory": self.directory,
            "event_notification": {
                "event_type": self.event_type,
                "old_entry": self.old_entry,
                "new_entry": self.new_entry,
            },
        }


class MetaLog:
    def __init__(self, capacity: int = 10000):
        # ts-ordered parallel lists; bisect on _ts makes read_since
        # O(log n + matches) instead of a full scan per subscriber poll
        self._events: list[MetaLogEvent] = []
        self._ts: list[int] = []
        self._capacity = capacity
        self._lock = threading.Lock()
        self._last_ts_ns = 0

    @property
    def last_ts_ns(self) -> int:
        return self._last_ts_ns

    def append(
        self,
        directory: str,
        event_type: str,
        old_entry: Optional[dict],
        new_entry: Optional[dict],
    ) -> MetaLogEvent:
        with self._lock:
            # strictly monotonic so since_ns resumption never duplicates
            ts = max(time.time_ns(), self._last_ts_ns + 1)
            self._last_ts_ns = ts
            ev = MetaLogEvent(ts, directory, event_type, old_entry, new_entry)
            self._events.append(ev)
            self._ts.append(ts)
            if len(self._events) > self._capacity * 2:
                del self._events[: -self._capacity]
                del self._ts[: -self._capacity]
            return ev

    def read_since(
        self, since_ns: int, path_prefix: str = "/"
    ) -> list[MetaLogEvent]:
        return self.read_since_with_watermark(since_ns, path_prefix)[0]

    def read_since_with_watermark(
        self, since_ns: int, path_prefix: str = "/"
    ) -> tuple[list[MetaLogEvent], int]:
        """-> (matching events, ts scanned through). The watermark is taken
        under the same lock as the slice, so resuming from it never skips
        events appended concurrently."""
        with self._lock:
            lo = bisect.bisect_right(self._ts, since_ns)
            tail = self._events[max(lo, len(self._events) - self._capacity):]
            watermark = self._last_ts_ns
        return [ev for ev in tail if _match_prefix(ev, path_prefix)], watermark

    async def subscribe(
        self,
        since_ns: int = 0,
        path_prefix: str = "/",
        poll_interval: float = 0.05,
        stopped=None,
    ) -> AsyncIterator[MetaLogEvent]:
        """Replay history after since_ns, then follow live
        (ref filer_grpc_server_sub_meta.go SubscribeMetadata loop)."""
        import asyncio

        cursor = since_ns
        while stopped is None or not stopped():
            # O(1) idle check: nothing appended since our cursor
            if self._last_ts_ns <= cursor:
                await asyncio.sleep(poll_interval)
                continue
            batch, watermark = self.read_since_with_watermark(
                cursor, path_prefix
            )
            cursor = max(cursor, watermark)
            for ev in batch:
                yield ev
            if not batch:
                await asyncio.sleep(poll_interval)


class DurableMetaLog(MetaLog):
    """MetaLog promoted from a bounded in-memory ring to a segmented
    on-disk log with resumable per-subscriber cursors (ISSUE 15).

    Layout: a directory of ``seg-<seq>.mlog`` files (msgpack record
    stream, `segment_events` records each), plus ``cursors.json``
    holding per-subscriber resume timestamps (shadow-write + atomic
    rename, the shard-map discipline). Appends go to disk FIRST (write
    + flush; fsync behind ``SEAWEEDFS_TPU_META_FEED_FSYNC`` — the
    store, not the feed, is the namespace durability authority), then
    into the inherited in-memory ring, which stays the fast tail for
    caught-up subscribers; a subscriber that fell behind the ring —
    or resumes in a fresh process — replays from the segments with the
    SAME exact-resumption guarantee the ring gives (strictly monotonic
    ts, watermark taken at the scan frontier), in bounded chunks.

    Retention is ``max_segments`` sealed segments; trimming records
    ``trimmed_through`` so a subscriber older than retention is
    detectable instead of silently incomplete. Torn tails (crash mid-
    append) are truncated at open — a partial record can never be
    replayed as an event.
    """

    def __init__(
        self,
        directory: str,
        capacity: int = 10000,
        segment_events: int = 4096,
        max_segments: int = 64,
        fsync: Optional[bool] = None,
    ):
        import msgpack
        import os

        super().__init__(capacity=capacity)
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.segment_events = max(16, segment_events)
        self.max_segments = max(2, max_segments)
        if fsync is None:
            fsync = (
                os.environ.get("SEAWEEDFS_TPU_META_FEED_FSYNC", "0") or "0"
            ) != "0"
        self.fsync = fsync
        self._packer = msgpack.Packer(use_bin_type=True)
        self.trimmed_through = 0  # ts through which history was dropped
        self._cursors: Optional[dict] = None
        # events at ts <= _mem_floor may be missing from the in-memory
        # ring — reads from at/below it go to the segments
        self._segments: list[dict] = []  # {seq, path, first, last, count}
        self._scan_segments()
        self._mem_floor = self._last_ts_ns
        if self._segments:
            active = self._segments[-1]
            self._active_f = open(active["path"], "ab")
        else:
            self._open_segment(1)
        self._publish_segment_gauge()

    # ---------------- segment plumbing ----------------
    def _seg_path(self, seq: int) -> str:
        import os

        return os.path.join(self.dir, f"seg-{seq}.mlog")

    def _scan_segments(self) -> None:
        import os

        seqs = sorted(
            int(fn[4:-5])
            for fn in os.listdir(self.dir)
            if fn.startswith("seg-") and fn.endswith(".mlog")
        )
        last_ts = 0
        for seq in seqs:
            path = self._seg_path(seq)
            first, last, count, good = self._scan_one(path)
            if count == 0 and seq != seqs[-1]:
                # empty mid-stack segment: drop it
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            # torn tail (crash mid-append): truncate to the valid prefix
            if good < os.path.getsize(path):
                with open(path, "ab") as f:
                    f.truncate(good)
            self._segments.append(
                {"seq": seq, "path": path, "first": first, "last": last,
                 "count": count}
            )
            last_ts = max(last_ts, last)
        self._last_ts_ns = last_ts

    @staticmethod
    def _scan_one(path: str) -> tuple[int, int, int, int]:
        """-> (first_ts, last_ts, count, good_bytes)."""
        import msgpack

        first = last = count = 0
        good = 0
        with open(path, "rb") as f:
            unpacker = msgpack.Unpacker(f, raw=False)
            while True:
                try:
                    rec = unpacker.unpack()
                except msgpack.OutOfData:
                    break
                except Exception:
                    break  # torn/garbage tail: keep the valid prefix
                if not isinstance(rec, dict) or "t" not in rec:
                    break
                ts = int(rec["t"])
                if count == 0:
                    first = ts
                last = ts
                count += 1
                good = unpacker.tell()  # bytes consumed by valid records
        return first, last, count, good

    def _open_segment(self, seq: int) -> None:
        self._segments.append(
            {"seq": seq, "path": self._seg_path(seq), "first": 0,
             "last": 0, "count": 0}
        )
        self._active_f = open(self._seg_path(seq), "ab")

    def _rotate_locked(self) -> None:
        import os

        self._active_f.flush()
        os.fsync(self._active_f.fileno())  # sealed segments are durable
        self._active_f.close()
        self._open_segment(self._segments[-1]["seq"] + 1)
        while len(self._segments) > self.max_segments:
            doomed = self._segments.pop(0)
            self.trimmed_through = max(
                self.trimmed_through, doomed["last"]
            )
            try:
                os.remove(doomed["path"])
            except OSError:
                pass
        self._publish_segment_gauge()

    def _publish_segment_gauge(self) -> None:
        try:
            from ..util.metrics import META_FEED_SEGMENTS
        except ImportError:
            return
        META_FEED_SEGMENTS.set(len(self._segments))

    # ---------------- append ----------------
    def append(self, directory, event_type, old_entry, new_entry):
        import os

        with self._lock:
            ts = max(time.time_ns(), self._last_ts_ns + 1)
            self._last_ts_ns = ts
            ev = MetaLogEvent(ts, directory, event_type, old_entry, new_entry)
            self._active_f.write(
                self._packer.pack(
                    {"t": ts, "d": directory, "e": event_type,
                     "o": old_entry, "n": new_entry}
                )
            )
            self._active_f.flush()
            if self.fsync:
                os.fsync(self._active_f.fileno())
            seg = self._segments[-1]
            if seg["count"] == 0:
                seg["first"] = ts
            seg["last"] = ts
            seg["count"] += 1
            self._events.append(ev)
            self._ts.append(ts)
            if len(self._events) > self._capacity * 2:
                # ring truncation: everything at/below the last dropped
                # event's ts now lives only in the segments
                self._mem_floor = self._ts[-self._capacity - 1]
                del self._events[: -self._capacity]
                del self._ts[: -self._capacity]
            if seg["count"] >= self.segment_events:
                self._rotate_locked()
        try:
            from ..util.metrics import META_FEED_EVENTS

            META_FEED_EVENTS.inc()
        except ImportError:
            pass
        return ev

    # ---------------- reads ----------------
    def read_since_with_watermark(
        self,
        since_ns: int,
        path_prefix: str = "/",
        limit: Optional[int] = None,
    ) -> tuple[list[MetaLogEvent], int]:
        """Exact resumption across the ring/segment boundary: when the
        cursor still falls inside the in-memory tail, this is the base
        ring read; otherwise events come off the segments in ts order.
        With `limit`, the returned watermark is the ts scanned THROUGH
        (the last examined event), so resuming from it never skips —
        a far-behind subscriber catches up in bounded chunks."""
        with self._lock:
            # the ring SERVES only its last `capacity` events (storage
            # runs to 2x between truncations) — the served floor is the
            # newest event the ring cannot produce
            if len(self._ts) > self._capacity:
                floor = self._ts[-self._capacity - 1]
            else:
                floor = self._mem_floor
            if since_ns >= floor:
                events, wm = self._ring_read(since_ns, path_prefix)
                if limit is not None and len(events) > limit:
                    events = events[:limit]
                    wm = events[-1].ts_ns
                return events, wm
            segs = [
                dict(s) for s in self._segments if s["last"] > since_ns
            ]
            watermark = self._last_ts_ns
        out: list[MetaLogEvent] = []
        scanned_through = since_ns
        for seg in segs:
            for ev in self._read_segment(seg["path"]):
                if ev.ts_ns <= since_ns:
                    continue
                scanned_through = ev.ts_ns
                if _match_prefix(ev, path_prefix):
                    out.append(ev)
                    if limit is not None and len(out) >= limit:
                        return out, scanned_through
        # the unlocked file scan may have read events appended AFTER the
        # watermark was captured — returning the stale watermark would
        # rewind the cursor below an already-delivered event (duplicate
        # delivery); the scan frontier is the resume authority
        return out, max(watermark, scanned_through)

    def _ring_read(self, since_ns, path_prefix):
        import bisect as _bisect

        lo = _bisect.bisect_right(self._ts, since_ns)
        tail = self._events[max(lo, len(self._events) - self._capacity):]
        return (
            [ev for ev in tail if _match_prefix(ev, path_prefix)],
            self._last_ts_ns,
        )

    @staticmethod
    def _read_segment(path: str):
        import msgpack

        try:
            with open(path, "rb") as f:
                for rec in msgpack.Unpacker(f, raw=False):
                    if not isinstance(rec, dict) or "t" not in rec:
                        break
                    yield MetaLogEvent(
                        int(rec["t"]), rec.get("d", ""), rec.get("e", ""),
                        rec.get("o"), rec.get("n"),
                    )
        except FileNotFoundError:
            return
        except Exception:
            return  # torn tail: the valid prefix was already yielded

    async def subscribe(
        self,
        since_ns: int = 0,
        path_prefix: str = "/",
        poll_interval: float = 0.05,
        stopped=None,
    ) -> AsyncIterator[MetaLogEvent]:
        """Replay durable history after since_ns in bounded chunks,
        then follow live (the base loop with a chunked disk read)."""
        import asyncio

        cursor = since_ns
        while stopped is None or not stopped():
            if self._last_ts_ns <= cursor:
                await asyncio.sleep(poll_interval)
                continue
            batch, watermark = self.read_since_with_watermark(
                cursor, path_prefix, limit=1024
            )
            cursor = max(cursor, watermark)
            for ev in batch:
                yield ev
            if not batch:
                await asyncio.sleep(poll_interval)

    # ---------------- per-subscriber cursors ----------------
    def _cursor_path(self) -> str:
        import os

        return os.path.join(self.dir, "cursors.json")

    def _load_cursors(self) -> dict:
        import json

        if self._cursors is None:
            try:
                with open(self._cursor_path()) as f:
                    self._cursors = {
                        str(k): int(v) for k, v in json.load(f).items()
                    }
            except (OSError, ValueError):
                self._cursors = {}
        return self._cursors

    def cursor_load(self, name: str) -> Optional[int]:
        """Resume point for a named subscriber, or None when unknown."""
        with self._lock:
            return self._load_cursors().get(name)

    def cursor_ack(self, name: str, ts_ns: int) -> None:
        """Record that `name` has processed through ts_ns (monotonic:
        an older ack never rewinds the cursor). Shadow-write + atomic
        rename — a crash mid-ack leaves the previous cursor, and
        resuming from it re-delivers only events whose effects are
        idempotent for a correctly written subscriber."""
        import json
        import os

        with self._lock:
            cur = self._load_cursors()
            if cur.get(name, -1) >= ts_ns:
                return
            cur[name] = int(ts_ns)
            tmp = self._cursor_path() + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(cur, f)
                os.replace(tmp, self._cursor_path())
            except OSError:
                pass  # cursor persistence is best-effort per ack

    def close(self) -> None:
        import os

        with self._lock:
            try:
                self._active_f.flush()
                os.fsync(self._active_f.fileno())
                self._active_f.close()
            except OSError:
                pass


def _match_prefix(ev: MetaLogEvent, path_prefix: str) -> bool:
    if not path_prefix or path_prefix == "/":
        return True
    for entry in (ev.new_entry, ev.old_entry):
        if entry:
            full = entry.get("full_path") or (
                f"{ev.directory.rstrip('/')}/{entry.get('name', '')}"
            )
            if full.startswith(path_prefix):
                return True
    return ev.directory.startswith(path_prefix)
