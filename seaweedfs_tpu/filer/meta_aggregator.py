"""Multi-filer metadata aggregation.

The reference runs several filers against shared or separate stores and
keeps them convergent by having every filer follow every peer's LOCAL
metadata stream, merging the events into an aggregate log that the public
SubscribeMetadata stream serves (ref: weed/filer2/meta_aggregator.go:19-80,
meta_replay.go; wiring in weed/server/filer_grpc_server_sub_meta.go).

Shape here: each FilerServer with `-peers` starts one follower task per
peer. Peer events are (a) appended to the aggregate MetaLog — so a watcher
of ANY filer sees the cluster-wide event stream — and (b) replayed into
the local store when the store is filer-local (separate per filer), which
is what keeps two filers over separate embedded stores convergent. Replay
writes go straight to the store, never through Filer.create_entry, so a
replayed event is not re-logged (no echo loops). Per-peer resume offsets
persist in a JSON sidecar, checkpointed every 100 changes or 60 s like the
reference (meta_aggregator.go:52-76).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Optional

from .entry import Entry
from .meta_log import MetaLog


class MetaAggregator:
    def __init__(
        self,
        filer,
        self_address: str,
        peers: list[str],
        replay_into_store: bool = True,
        offsets_path: str = "",
        capacity: int = 10000,
    ):
        self.filer = filer
        self.self_address = self_address
        self.peers = [p for p in peers if p and p != self_address]
        self.replay_into_store = replay_into_store
        self.log = MetaLog(capacity=capacity)
        self._offsets_path = offsets_path
        self._offsets: dict = {}
        self._changes_since_persist = 0
        self._last_persist = time.monotonic()
        self._tasks: list = []
        self._stopped = False
        if offsets_path and os.path.exists(offsets_path):
            try:
                with open(offsets_path) as f:
                    self._offsets = {
                        k: int(v) for k, v in json.load(f).items()
                    }
            except (OSError, ValueError):
                self._offsets = {}

    # ---------------- lifecycle ----------------
    def start(self) -> None:
        # local events feed the aggregate stream too (reference: the local
        # log buffer IS one of the aggregated inputs)
        self._tasks.append(asyncio.ensure_future(self._follow_local()))
        for peer in self.peers:
            self._tasks.append(
                asyncio.ensure_future(self._follow_peer(peer))
            )

    async def stop(self) -> None:
        self._stopped = True
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        self._persist_offsets(force=True)

    # ---------------- followers ----------------
    async def _follow_local(self) -> None:
        from ..util import log as _log
        from .meta_log import MetaLogTrimmed

        since = 0
        while not self._stopped:
            try:
                async for ev in self.filer.meta_log.subscribe(
                    since, "/", stopped=lambda: self._stopped
                ):
                    since = ev.ts_ns
                    self.log.append(
                        ev.directory, ev.event_type, ev.old_entry,
                        ev.new_entry,
                    )
                return
            except MetaLogTrimmed as e:
                # the local durable log lost a range (retention outran
                # this follower, or a corrupt segment): the aggregate
                # ring is lossy by design — log the gap and resume past
                # it instead of dying silently
                _log.warning(
                    "local meta feed gap (%d, %d]: resuming past it",
                    e.since_ns, e.trimmed_through,
                )
                since = max(since, e.trimmed_through)

    async def _follow_peer(self, peer: str) -> None:
        """Follow one peer's SubscribeLocalMetadata stream forever,
        redialing with backoff (ref meta_aggregator.go:98-128; the 1733 ms
        retry sleep is the reference's)."""
        from ..pb import grpc_address
        from ..pb.rpc import Stub
        from ..util import log as _log

        since = self._offsets.get(peer, 0)
        while not self._stopped:
            try:
                stub = Stub(grpc_address(peer), "filer")
                async for msg in stub.server_stream(
                    "SubscribeLocalMetadata",
                    {
                        "client_name": f"filer:{self.self_address}",
                        "path_prefix": "/",
                        "since_ns": since,
                    },
                ):
                    notif = msg.get("event_notification") or {}
                    self.log.append(
                        msg.get("directory", ""),
                        notif.get("event_type", ""),
                        notif.get("old_entry"),
                        notif.get("new_entry"),
                    )
                    if self.replay_into_store:
                        try:
                            self._replay(notif)
                        except Exception as e:
                            _log.warning(
                                "meta replay from %s failed: %s", peer, e
                            )
                    since = int(msg.get("ts_ns", since)) or since
                    self._offsets[peer] = since
                    self._changes_since_persist += 1
                    self._maybe_persist()
            except asyncio.CancelledError:
                return
            except Exception as e:
                _log.warning("subscribing %s meta change: %s", peer, e)
            if not self._stopped:
                await asyncio.sleep(1.733)

    # ---------------- replay (ref meta_replay.go) ----------------
    def _replay(self, notif: dict) -> None:
        """Apply one peer event to the LOCAL store directly — not through
        Filer.create_entry — so it is not re-logged locally."""
        store = self.filer.store
        old, new = notif.get("old_entry"), notif.get("new_entry")
        if old and (
            not new or old.get("full_path") != new.get("full_path")
        ):
            store.delete_entry(old["full_path"])
        if new:
            entry = Entry.from_dict(new)
            self._ensure_parents(entry.full_path)
            store.insert_entry(entry)

    def _ensure_parents(self, full_path: str) -> None:
        store = self.filer.store
        parts = full_path.strip("/").split("/")[:-1]
        path = ""
        for part in parts:
            path += "/" + part
            if store.find_entry(path) is None:
                from .entry import Attr

                store.insert_entry(
                    Entry(
                        full_path=path,
                        attr=Attr(mtime=time.time(), mode=0o40755),
                    )
                )

    # ---------------- offset checkpointing ----------------
    def _maybe_persist(self) -> None:
        if self._changes_since_persist >= 100 or (
            time.monotonic() - self._last_persist > 60
        ):
            self._persist_offsets()

    def _persist_offsets(self, force: bool = False) -> None:
        if not self._offsets_path:
            return
        if not force and not self._offsets:
            return
        try:
            tmp = self._offsets_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self._offsets, f)
            os.replace(tmp, self._offsets_path)
            self._changes_since_persist = 0
            self._last_persist = time.monotonic()
        except OSError:
            pass
