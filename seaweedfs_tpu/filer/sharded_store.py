"""Prefix-sharded filer store: metadata QPS that scales with cores.

One filer store serializes ALL metadata traffic behind a single lock
(and, for the sqlite/LSM kinds, a single B-tree/WAL) — the
single-process metadata ceiling the ROADMAP names as the prerequisite
for serving millions of users. `ShardedFilerStore` partitions the
namespace by DIRECTORY-prefix ranges across N underlying stores of any
existing kind (memory / sqlite / LSM / log), the multi-chip
partitioning pattern of "Large Scale Distributed Linear Algebra With
TPUs" (arxiv 2112.09017) applied to the metadata plane:

- **routing is by directory**: every entry of one directory lives in
  exactly ONE shard, so `list_directory_entries` (and therefore
  `scan_subtree` / S3 LIST, which pull per-directory pages) hits a
  single shard per directory and stitches across shard boundaries in
  exact key order with no merge pass;
- **the shard map is crash-safe**: an ordered list of split points over
  directory paths, committed via the repo's shadow-write discipline
  (`SHARDMAP.shadow` -> fsync -> atomic rename, the `.nmm`/`.ctm`
  construction). Routing consults ONLY the committed map, so no path
  ever resolves to two shards — mid-rebalance copies in the destination
  store are invisible until the commit points at them;
- **rebalance is heat-driven**: one `storage/heat.HeatTracker` per
  shard (exponential decay, half-life `SEAWEEDFS_TPU_META_HEAT_HALFLIFE`)
  accumulates op heat; when one shard's heat exceeds
  `rebalance_factor` x the mean (and an absolute floor, and a holddown
  interval — the lifecycle plane's anti-flap hysteresis), half of its
  directories move to the cooler adjacent shard;
- **moves are crash-safe by step order** (the cold-tier offload
  discipline): (purge) destination range cleared of stale copies ->
  (copy) entries duplicated into the destination -> (commit) new bounds
  + a cleanup obligation written shadow-first -> (cleanup) source range
  deleted and the obligation cleared. A kill before commit leaves the
  source authoritative (copies inert, re-purged on retry); a kill after
  commit leaves the destination authoritative (the recorded obligation
  re-runs cleanup at the next open). `tests/test_meta_plane.py` drives
  a kill-point grid over every step;
- **moves never lose live traffic**: store ops take a shared (reader)
  slot on a writer-preferring RW lock. Without coordination, a write
  routed to the source shard between the copy pass and cleanup would
  be swept by cleanup (lost write), a delete in the same window would
  resurrect from the destination copy, and a read could probe stale
  routing around the bounds flip. But the O(range) copy pass must not
  stall the serving event loop either, so a move holds the exclusive
  (writer) slot only BRIEFLY: it opens a dirty window, releases the
  lock for purge+copy (routing still points at the source, so the
  destination copies are invisible and concurrent mutators proceed —
  each records its path if it lands in the moving range), then
  re-acquires exclusivity to replay that delta, flip the bounds, and
  clean up. The exclusive window is O(mutations-during-copy), not
  O(range).

`find_many` is the gate-batched lookup seam (`filer/meta_gate.py`):
paths group by shard and the per-shard batches run in parallel worker
threads — the sqlite/LSM stores release the GIL inside their C probe,
so metadata lookups become data-parallel across shards the way
`BatchLookupGate` makes needle probes data-parallel across a batch.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from typing import Callable, Optional

from .entry import Entry
from .filer_store import _split

MAP_NAME = "SHARDMAP"
SHADOW_SUFFIX = ".shadow"

# rebalance hysteresis knobs (docs/perf.md "Metadata plane")
REBALANCE_FACTOR = float(
    os.environ.get("SEAWEEDFS_TPU_META_REBALANCE_FACTOR", "4") or 4.0
)
REBALANCE_MIN_HEAT = float(
    os.environ.get("SEAWEEDFS_TPU_META_REBALANCE_MIN_HEAT", "32") or 32.0
)
REBALANCE_MIN_INTERVAL_S = float(
    os.environ.get("SEAWEEDFS_TPU_META_REBALANCE_INTERVAL", "60") or 60.0
)

# rebalance step names in execution order — the kill-point grid in
# tests/test_meta_plane.py enumerates exactly these. "intent" is the
# write-ahead record of the move range: without it, a crash between
# copy and commit would strand copies in the destination that a LATER
# retry (possibly choosing a different split) would never purge.
# "delta" marks the end of the unlocked copy window: mutations recorded
# during purge/copy are replayed under the exclusive lock right after.
REBALANCE_STEPS = ("intent", "purge", "copy", "delta", "commit", "cleanup")

# find_many batches below this run their per-shard probes inline:
# measured on the dev host, worker-thread dispatch + GIL ping-pong
# costs more than a gate-tick-sized per-shard C query saves — only
# bulk resolutions (cold scans, rebalance-scale probes) clear the bar
_PARALLEL_THRESHOLD = int(
    os.environ.get("SEAWEEDFS_TPU_META_PARALLEL_BATCH", "2048") or 2048
)

_BOUND_CHARSET = "0123456789abcdefghijklmnopqrstuvwxyz"


def default_bounds(n_shards: int) -> list[str]:
    """N-1 split points spreading top-level names over [0-9a-z] — the
    data-free initial partition; rebalance corrects real skew."""
    if n_shards <= 1:
        return []
    step = len(_BOUND_CHARSET) / n_shards
    return [
        "/" + _BOUND_CHARSET[min(int(round((i + 1) * step)),
                                 len(_BOUND_CHARSET) - 1)]
        for i in range(n_shards - 1)
    ]


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _RWLock:
    """Writer-preferring readers-writer lock for shard topology.

    Store ops are readers: they may run concurrently (each sub-store
    serializes its own state) but must observe a stable bounds/route
    and must never land inside a move's copy->cleanup window. A
    rebalance move is the writer: exclusive, so no concurrent mutator
    can be swept by cleanup or resurrected from a stale copy. Writer
    preference (new readers queue once a writer waits) keeps a steady
    read load from starving the rebalance forever.
    """

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    @contextmanager
    def read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1
        try:
            yield
        finally:
            with self._cond:
                self._readers -= 1
                if self._readers == 0:
                    self._cond.notify_all()

    @contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


def _count_shard_op(op: str) -> None:
    try:
        from ..util.metrics import META_SHARD_OPS
    except ImportError:
        return
    META_SHARD_OPS.inc(op=op)


class ShardedFilerStore:
    """FilerStore over N sub-stores partitioned by directory-path range.

    `factory(name)` builds one underlying store per shard (any kind);
    `directory` holds the crash-safe shard map. An existing SHARDMAP
    wins over `n_shards`/`initial_bounds` (the map is the authority,
    constructor args only seed a fresh store).
    """

    def __init__(
        self,
        directory: str,
        factory: Callable[[str], object],
        n_shards: int = 4,
        initial_bounds: Optional[list[str]] = None,
        heat_half_life_s: Optional[float] = None,
        rebalance_factor: float = 0.0,
        rebalance_min_heat: float = 0.0,
        rebalance_min_interval_s: Optional[float] = None,
        clock: Callable[[], float] = time.time,
        step_hook: Optional[Callable[[str], None]] = None,
    ):
        from ..storage.heat import HeatTracker

        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self._factory = factory
        self._clock = clock
        self.step_hook = step_hook
        self.rebalance_factor = rebalance_factor or REBALANCE_FACTOR
        self.rebalance_min_heat = rebalance_min_heat or REBALANCE_MIN_HEAT
        self.rebalance_min_interval_s = (
            rebalance_min_interval_s
            if rebalance_min_interval_s is not None
            else REBALANCE_MIN_INTERVAL_S
        )
        # _rw: topology lock — ops shared, move delta/commit exclusive
        # (see _RWLock); _lock: small mutex for lazy-init + dirty state
        self._rw = _RWLock()
        self._lock = threading.Lock()
        self._pool: Optional[ThreadPoolExecutor] = None
        # in-flight move dirty window: (move_lo, move_hi) while the
        # unlocked copy pass runs; mutators landing in the range record
        # their paths for the pre-commit delta replay. _move_mutex
        # serializes whole moves against each other (bounds + the
        # pending-cleanup obligation are single-writer) without
        # touching the reader path.
        self._move_prep: Optional[tuple[str, str]] = None
        self._move_dirty: set = set()
        self._move_dirty_full = False
        self._move_mutex = threading.Lock()
        self._last_rebalance = 0.0
        self.stats = {
            "ops": 0,
            "batched_lookups": 0,
            "batches": 0,
            "rebalances": 0,
            "moved_entries": 0,
        }

        mf = self._load_map()
        if mf is None:
            names = [f"shard-{i}" for i in range(max(1, n_shards))]
            bounds = (
                list(initial_bounds)
                if initial_bounds is not None
                else default_bounds(len(names))
            )
            if len(bounds) != len(names) - 1:
                raise ValueError(
                    f"{len(names)} shards need {len(names) - 1} bounds, "
                    f"got {len(bounds)}"
                )
            if bounds != sorted(bounds):
                raise ValueError("initial_bounds must be sorted")
            self._names = names
            self._bounds = bounds
            self._pending_cleanup = None
            self._pending_move = None
            self._commit_map()
        else:
            self._names = [str(n) for n in mf["names"]]
            self._bounds = [str(b) for b in mf["bounds"]]
            self._pending_cleanup = mf.get("pending_cleanup")
            self._pending_move = mf.get("pending_move")
        self._stores = [factory(name) for name in self._names]
        self._heat = [
            HeatTracker(half_life_s=heat_half_life_s, clock=clock)
            for _ in self._names
        ]
        # crash recovery, in intent order: an aborted move (intent
        # recorded, bounds never committed) is rolled back by purging
        # the destination of the attempted copies; a committed move
        # missing only its cleanup finishes it
        if self._pending_move:
            self._abort_pending_move()
        if self._pending_cleanup:
            self._run_cleanup()
        self._publish_gauges()

    # ---------------- shard map persistence ----------------
    def _map_path(self) -> str:
        return os.path.join(self.dir, MAP_NAME)

    def _load_map(self) -> Optional[dict]:
        shadow = self._map_path() + SHADOW_SUFFIX
        if os.path.exists(shadow):
            # a torn shadow is never read as authority (the .ctm sweep)
            try:
                os.remove(shadow)
            except OSError:
                pass
        path = self._map_path()
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                mf = json.load(f)
        except (OSError, ValueError):
            raise RuntimeError(f"unreadable shard map {path!r}")
        if (
            not isinstance(mf, dict)
            or mf.get("version") != 1
            or len(mf.get("bounds", [])) != len(mf.get("names", [])) - 1
        ):
            raise RuntimeError(f"malformed shard map {path!r}")
        return mf

    def _commit_map(self) -> None:
        """Shadow-write + fsync + atomic rename: the committed map IS
        shard ownership — a reader never sees a torn or partial map."""
        path = self._map_path()
        shadow = path + SHADOW_SUFFIX
        payload = json.dumps(
            {
                "version": 1,
                "names": self._names,
                "bounds": self._bounds,
                "pending_cleanup": self._pending_cleanup,
                "pending_move": self._pending_move,
            },
            sort_keys=True,
        )
        with open(shadow, "w") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(shadow, path)
        _fsync_dir(self.dir)

    def _publish_gauges(self) -> None:
        try:
            from ..util.metrics import META_SHARD_COUNT
        except ImportError:
            return
        META_SHARD_COUNT.set(len(self._stores))

    # ---------------- routing ----------------
    def _index_for_dir(self, d: str) -> int:
        return bisect.bisect_right(self._bounds, d)

    def _shard_for(self, full_path: str):
        d, _name = _split(full_path)
        return self._stores[self._index_for_dir(d)]

    def _indices_for_range(self, lo: str, hi: str) -> range:
        """Shard indices whose directory range can intersect [lo, hi)."""
        first = bisect.bisect_right(self._bounds, lo)
        last = bisect.bisect_left(self._bounds, hi)
        return range(first, last + 1)

    def shard_of(self, full_path: str) -> int:
        """Index of the one shard owning this path (test visibility)."""
        d, _ = _split(full_path)
        return self._index_for_dir(d)

    # ---------------- FilerStore interface ----------------
    def _note_move_dirty(self, d: str, full_path: str) -> None:
        """Record a mutation landing inside an in-flight move's range.
        The copy pass runs without the exclusive lock (so it cannot see
        this write); the mover replays the dirty set under the lock
        before committing the bounds — no write is ever swept by
        cleanup, no delete ever resurrects from a stale copy. Called
        with the read lock held, so the window flags cannot flip
        mid-op."""
        mp = self._move_prep
        if mp is not None and mp[0] <= d < mp[1]:
            with self._lock:
                self._move_dirty.add(full_path)

    def insert_entry(self, entry: Entry) -> None:
        with self._rw.read():
            d, _ = _split(entry.full_path)
            i = self._index_for_dir(d)
            self._heat[i].note_write()
            self.stats["ops"] += 1
            _count_shard_op("insert")
            self._note_move_dirty(d, entry.full_path)
            self._stores[i].insert_entry(entry)

    update_entry = insert_entry

    def insert_many(self, entries: list[Entry]) -> None:
        """Batched upsert (the write-gate seam): group by owning shard
        and hand each shard its whole group in ONE insert_many round —
        a gate flush costs O(shards-touched) store round-trips, not
        O(entries). Dirty-window discipline matches insert_entry: every
        path landing in an in-flight move's range is recorded under the
        same read lock."""
        if not entries:
            return
        with self._rw.read():
            self.stats["ops"] += 1
            _count_shard_op("insert_many")
            by_shard: dict[int, list[Entry]] = {}
            for entry in entries:
                d, _ = _split(entry.full_path)
                i = self._index_for_dir(d)
                self._note_move_dirty(d, entry.full_path)
                by_shard.setdefault(i, []).append(entry)
            for i, group in by_shard.items():
                self._heat[i].note_write(len(group))
                im = getattr(self._stores[i], "insert_many", None)
                if im is not None:
                    im(group)
                else:
                    for entry in group:
                        self._stores[i].insert_entry(entry)

    @property
    def write_rounds(self) -> int:
        """Sum of the sub-stores' write round-trips (see
        MemoryFilerStore.write_rounds) — what the coalescing bench
        counts."""
        return sum(
            getattr(s, "write_rounds", 0) for s in self._stores
        )

    def find_entry(self, full_path: str) -> Optional[Entry]:
        with self._rw.read():
            d, _ = _split(full_path)
            i = self._index_for_dir(d)
            self._heat[i].note_read()
            self.stats["ops"] += 1
            _count_shard_op("find")
            return self._stores[i].find_entry(full_path)

    def delete_entry(self, full_path: str) -> None:
        with self._rw.read():
            d, _ = _split(full_path)
            i = self._index_for_dir(d)
            self._heat[i].note_write()
            self.stats["ops"] += 1
            _count_shard_op("delete")
            self._note_move_dirty(d, full_path)
            self._stores[i].delete_entry(full_path)

    def delete_folder_children(self, full_path: str) -> None:
        """A subtree spans shards: its directories occupy the string
        range [prefix, successor(prefix + "/")) — fan the delete to
        every shard that range can touch (the op is a no-op on shards
        holding none of it)."""
        from .filer_store import prefix_successor

        prefix = full_path.rstrip("/")
        hi = prefix_successor(prefix + "/") or "\U0010ffff"
        with self._rw.read():
            self.stats["ops"] += 1
            _count_shard_op("delete_children")
            mp = self._move_prep
            if mp is not None and mp[0] < hi and prefix < mp[1]:
                # a range op intersecting the moving range: per-path
                # dirty tracking cannot name its victims — mark the
                # whole move dirty so the delta replay recopies exactly
                with self._lock:
                    self._move_dirty_full = True
            for i in self._indices_for_range(prefix, hi):
                self._heat[i].note_write()
                self._stores[i].delete_folder_children(full_path)

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> list[Entry]:
        with self._rw.read():
            d = dir_path.rstrip("/") or "/"
            i = self._index_for_dir(d)
            self._heat[i].note_read()
            self.stats["ops"] += 1
            _count_shard_op("list")
            return self._stores[i].list_directory_entries(
                dir_path, start_file_name, inclusive, limit
            )

    def scan_directory_entries(
        self,
        dir_path: str,
        start_file_name: str,
        inclusive: bool,
        limit: int,
        upper_bound: str = "",
    ) -> list[Entry]:
        """Upper-bound pushdown passthrough: the owning shard's indexed
        range scan when it has one (sqlite), its plain page otherwise."""
        with self._rw.read():
            d = dir_path.rstrip("/") or "/"
            i = self._index_for_dir(d)
            self._heat[i].note_read()
            store = self._stores[i]
            scan = getattr(store, "scan_directory_entries", None)
            if scan is not None:
                return scan(dir_path, start_file_name, inclusive, limit,
                            upper_bound)
            return store.list_directory_entries(
                dir_path, start_file_name, inclusive, limit
            )

    # ---------------- batched lookups (the gate seam) ----------------
    def find_many(self, paths: list[str]) -> dict[str, Entry]:
        """One columnar probe for MANY paths: group by owning shard,
        run the per-shard batches in parallel worker threads (sqlite /
        LSM release the GIL inside the probe), merge. The gate
        (`filer/meta_gate.py`) feeds whole event-loop wakeups of
        concurrent probes through here."""
        if not paths:
            return {}
        with self._rw.read():
            self.stats["batched_lookups"] += len(paths)
            self.stats["batches"] += 1
            _count_shard_op("find_many")
            by_shard: dict[int, list[str]] = {}
            for p in paths:
                d, _ = _split(p)
                by_shard.setdefault(self._index_for_dir(d), []).append(p)
            for i in by_shard:
                self._heat[i].note_read(len(by_shard[i]))
            # thread fan-out only pays once the per-shard batches
            # amortize the dispatch/wakeup cost; a gate-tick-sized batch
            # runs the per-shard probes inline (each is one lock + one
            # C query)
            if len(by_shard) == 1 or len(paths) < _PARALLEL_THRESHOLD:
                out: dict[str, Entry] = {}
                for i, group in by_shard.items():
                    out.update(
                        self._shard_find_many(self._stores[i], group)
                    )
                return out
            pool = self._pool
            if pool is None:
                # double-checked: find_many runs concurrently from many
                # gate executor threads (readers share _rw) — exactly
                # one of them may create the pool
                with self._lock:
                    pool = self._pool
                    if pool is None:
                        pool = self._pool = ThreadPoolExecutor(
                            max_workers=len(self._stores),
                            thread_name_prefix="meta-shard",
                        )
            futs = [
                pool.submit(self._shard_find_many, self._stores[i], group)
                for i, group in by_shard.items()
            ]
            out = {}
            for f in futs:
                out.update(f.result())
            return out

    @staticmethod
    def _shard_find_many(store, paths: list[str]) -> dict[str, Entry]:
        fm = getattr(store, "find_many", None)
        if fm is not None:
            return fm(paths)
        out = {}
        for p in paths:
            e = store.find_entry(p)
            if e is not None:
                out[p] = e
        return out

    # ---------------- heat + rebalance ----------------
    def shard_heats(self, now: Optional[float] = None) -> list[float]:
        return [
            h.read_heat(now) + h.write_heat(now) for h in self._heat
        ]

    def maybe_rebalance(self, now: Optional[float] = None) -> Optional[dict]:
        """Hysteresis gate in front of `rebalance_once`: fire only when
        one shard's decayed heat exceeds `rebalance_factor` x the mean
        AND an absolute floor, and not within the holddown interval of
        the previous move — idle clusters and mild skew never churn
        metadata (the lifecycle planner's anti-flap discipline)."""
        t = self._clock() if now is None else now
        if t - self._last_rebalance < self.rebalance_min_interval_s:
            return None
        heats = self.shard_heats(now)
        hottest = max(range(len(heats)), key=heats.__getitem__)
        mean = sum(heats) / len(heats)
        if heats[hottest] < self.rebalance_min_heat:
            return None
        if heats[hottest] < self.rebalance_factor * max(mean, 1e-9):
            return None
        return self.rebalance_once(hottest, now=now)

    def rebalance_once(
        self, src: Optional[int] = None, now: Optional[float] = None
    ) -> Optional[dict]:
        """Move half of one shard's directories to its cooler adjacent
        neighbor (intent -> purge -> copy -> delta -> commit -> cleanup;
        see module doc for the crash analysis). The exclusive writer
        slot is held only for the intent and the delta+commit — every
        O(range) pass (candidate enumeration, purge, copy, cleanup)
        runs with concurrent ops flowing: routing still points the
        range at its committed owner throughout, and `_move_mutex`
        serializes whole moves against each other. A failed move rolls
        back in place (destination purged, intent cleared) so a retry
        starts clean without waiting for a process restart. Returns a
        move report or None when the shard cannot shed (single
        directory, no neighbor, or another move in flight)."""
        hook = self.step_hook or (lambda step: None)
        if not self._move_mutex.acquire(blocking=False):
            return None  # another move is mid-flight
        try:
            if self._pending_move:
                # a previous in-process attempt failed to roll back
                # (e.g. the abort's own map write failed): finish that
                # rollback before starting a new move, or its strays
                # would be orphaned by our intent overwrite
                self._abort_pending_move()
            if self._pending_cleanup:
                # likewise a cleanup that failed mid-delete: finish it
                # before commit durably overwrites the obligation with
                # our own (idempotent, same as the at-open recovery)
                self._run_cleanup()
            # candidate selection reads bounds + enumerates the shard
            # WITHOUT any topology lock: only moves mutate bounds, and
            # the move mutex is ours
            heats = self.shard_heats(now)
            if src is None:
                src = max(range(len(heats)), key=heats.__getitem__)
            if len(self._stores) < 2:
                return None
            neighbors = [
                j for j in (src - 1, src + 1) if 0 <= j < len(self._stores)
            ]
            dst = min(neighbors, key=heats.__getitem__)
            lo, hi = self._shard_range(src)
            dirs = sorted(
                {d for d, _n, _e in self._iter_store(src, lo, hi)}
            )
            if len(dirs) < 2:
                return None  # a single directory cannot split
            if dst < src:
                # raise the lower bound: dirs below the median move left
                split = dirs[len(dirs) // 2]
                move_lo, move_hi = lo, split
                new_bounds = list(self._bounds)
                new_bounds[dst] = split
            else:
                # lower the upper bound: dirs at/after the median move right
                split = dirs[len(dirs) // 2]
                move_lo, move_hi = split, hi
                new_bounds = list(self._bounds)
                new_bounds[src] = split

            with self._rw.write():
                # (intent) write-ahead record of the move range: a crash
                # anywhere before commit rolls back by purging exactly
                # this range from the destination at the next open — a
                # retry is free to choose a different split
                hook("intent")
                self._pending_move = {
                    "src": src, "dst": dst, "lo": move_lo, "hi": move_hi,
                }
                self._commit_map()
                # open the dirty window before surrendering exclusivity
                self._move_prep = (move_lo, move_hi)
                self._move_dirty = set()
                self._move_dirty_full = False

            try:
                # (purge)+(copy) run WITHOUT the exclusive lock: the
                # committed map still routes the range to the source, so
                # the destination copies stay invisible; concurrent
                # mutators proceed and are delta-recorded
                # (purge) clear stale copies an earlier same-range
                # attempt may have left in the destination — an entry
                # deleted at the source since then must not resurrect
                hook("purge")
                for _d, _n, e in list(
                    self._iter_store(dst, move_lo, move_hi)
                ):
                    self._stores[dst].delete_entry(e.full_path)

                hook("copy")
                moved = 0
                for _d, _n, e in list(
                    self._iter_store(src, move_lo, move_hi)
                ):
                    self._stores[dst].insert_entry(e)
                    moved += 1
                # (delta-point) mutations recorded up to here live only
                # in the source + the dirty set; the replay below is
                # what carries them across
                hook("delta")

                with self._rw.write():
                    try:
                        # (delta) replay what changed during the
                        # unlocked copy: the source is still
                        # authoritative for the range, so re-reading
                        # each dirty path gives the final word
                        if self._move_dirty_full:
                            # a subtree delete crossed the range —
                            # recopy exactly
                            for _d, _n, e in list(
                                self._iter_store(dst, move_lo, move_hi)
                            ):
                                self._stores[dst].delete_entry(e.full_path)
                            moved = 0
                            for _d, _n, e in list(
                                self._iter_store(src, move_lo, move_hi)
                            ):
                                self._stores[dst].insert_entry(e)
                                moved += 1
                        else:
                            for p in self._move_dirty:
                                e = self._stores[src].find_entry(p)
                                if e is None:
                                    self._stores[dst].delete_entry(p)
                                else:
                                    self._stores[dst].insert_entry(e)

                        hook("commit")
                        old_state = (
                            self._bounds,
                            self._pending_move,
                            self._pending_cleanup,
                        )
                        self._bounds = new_bounds
                        self._pending_move = None
                        self._pending_cleanup = {
                            "shard": src, "lo": move_lo, "hi": move_hi,
                        }
                        try:
                            self._commit_map()
                        except BaseException:
                            # the durable map still holds the OLD bounds
                            # + intent: memory must agree, or writes
                            # routed by the new bounds would be purged
                            # as intent strays at the next open
                            (
                                self._bounds,
                                self._pending_move,
                                self._pending_cleanup,
                            ) = old_state
                            raise
                    finally:
                        self._move_prep = None
                        self._move_dirty = set()
                        self._move_dirty_full = False
            except BaseException:
                # roll back IN PLACE (the at-open recovery shape, minus
                # the restart): close the dirty window, purge the
                # attempted copies, clear the intent — a later move
                # with a different split must not inherit strays
                with self._rw.write():
                    self._move_prep = None
                    self._move_dirty = set()
                    self._move_dirty_full = False
                self._abort_pending_move()
                raise

            # (cleanup) runs WITHOUT the exclusive lock too: the
            # committed bounds no longer route the moved range to the
            # source, so live traffic cannot touch what it deletes; the
            # move mutex keeps it ordered before any next move
            hook("cleanup")
            self._run_cleanup()

            # the moved range's heat follows the data (seed, like
            # re-inflation hands EC heat to the fresh volume)
            share = 0.5
            t = self._clock() if now is None else now
            src_r = self._heat[src].read_heat(t)
            src_w = self._heat[src].write_heat(t)
            self._heat[src].seed(src_r * (1 - share), src_w * (1 - share))
            dst_r = self._heat[dst].read_heat(t)
            dst_w = self._heat[dst].write_heat(t)
            self._heat[dst].seed(dst_r + src_r * share,
                                 dst_w + src_w * share)

            self._last_rebalance = t
            self.stats["rebalances"] += 1
            self.stats["moved_entries"] += moved
            try:
                from ..util.metrics import (
                    META_SHARD_MOVED,
                    META_SHARD_REBALANCES,
                )

                META_SHARD_REBALANCES.inc()
                if moved:
                    META_SHARD_MOVED.inc(moved)
            except ImportError:
                pass
            return {
                "src": src, "dst": dst, "split": split, "moved": moved,
            }
        finally:
            self._move_mutex.release()

    def _shard_range(self, i: int) -> tuple[str, str]:
        lo = self._bounds[i - 1] if i > 0 else ""
        hi = self._bounds[i] if i < len(self._bounds) else "\U0010ffff"
        return lo, hi

    def _iter_store(self, i: int, lo: str, hi: str):
        """(directory, name, Entry) of shard i with lo <= directory < hi,
        via the store's `iter_all` bulk accessor."""
        for d, name, e in self._stores[i].iter_all():
            if lo <= d < hi:
                yield d, name, e

    def _abort_pending_move(self) -> None:
        """Roll back a move whose bounds were never committed: the
        committed map still routes the range to the source, so any
        copies in the destination are inert duplicates — purge exactly
        the recorded range, then clear the intent (idempotent)."""
        mv = self._pending_move
        if not mv:
            return
        dst = int(mv["dst"])
        lo, hi = str(mv["lo"]), str(mv["hi"])
        own_lo, own_hi = self._shard_range(dst)
        for d, _n, e in list(self._iter_store(dst, lo, hi)):
            if not (own_lo <= d < own_hi):
                self._stores[dst].delete_entry(e.full_path)
        self._pending_move = None
        self._commit_map()

    def _run_cleanup(self) -> None:
        """Finish a committed move: delete the moved range from the old
        owner, then clear the obligation (idempotent — re-run at open
        after a crash)."""
        ob = self._pending_cleanup
        if not ob:
            return
        i = int(ob["shard"])
        lo, hi = str(ob["lo"]), str(ob["hi"])
        own_lo, own_hi = self._shard_range(i)
        for d, _n, e in list(self._iter_store(i, lo, hi)):
            # only sweep what the committed map no longer routes here
            if not (own_lo <= d < own_hi):
                self._stores[i].delete_entry(e.full_path)
        self._pending_cleanup = None
        self._commit_map()

    # ---------------- admin ----------------
    def iter_all(self):
        """Every (directory, name, Entry) across shards — NOT in global
        key order (per-shard order only); callers needing order sort."""
        for i in range(len(self._stores)):
            yield from self._stores[i].iter_all()

    def describe(self) -> dict:
        return {
            "shards": len(self._stores),
            "bounds": list(self._bounds),
            "heats": [round(h, 3) for h in self.shard_heats()],
            "stats": dict(self.stats),
        }

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        for s in self._stores:
            closer = getattr(s, "close", None)
            if closer is not None:
                closer()
