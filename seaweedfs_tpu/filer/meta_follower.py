"""Meta-log-fed read replicas (ISSUE 20 tentpole 3).

A follower filer (`weed filer -followSource <primary>`) tails the
primary's ``SubscribeMetadata`` stream from a locally-durable cursor and
applies every namespace event STRAIGHT to its own store — same-cluster
semantics, so unlike the geo replicator (replication/geo.py) it never
ships chunk bytes and NEVER frees chunks on delete: the primary owns the
data plane, the follower only mirrors metadata. GET/LIST served from the
follower are eventually consistent with a DISCLOSED staleness bound:

    bound = now - head_checked_at          if cursor >= head_ts
          = now - cursor / 1e9             otherwise

where ``head_ts`` is the primary's ``last_ts_ns`` observed at
``head_checked_at`` (a periodic GetFilerConfiguration probe). Both arms
are provable over-estimates of any divergent answer's age: an event the
follower is missing either existed at the last head check (so its ts is
above the cursor, making it younger than ``now - cursor``) or was
appended after the check (younger than ``now - head_checked_at``).

Read-your-writes rides a counted redirect: a client that just wrote to
the primary holds the write's ``ts_ns`` watermark and sends it as
``min_ts_ns`` on follower reads; a follower whose cursor is behind the
watermark answers ``{"error": "redirect", "primary": ...}`` instead of a
stale entry (``meta_follower_redirects_total``).

A cursor that falls behind the primary's meta-log retention
(MetaLogTrimmed under ``strict_resume``) halts the tail LOUDLY with
``resync_required`` — silently skipping the hole would serve a namespace
missing arbitrary mutations forever.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import time
from typing import Callable, Optional

from ..pb import grpc_address
from ..pb.rpc import Stub
from ..util import log as _log
from ..util.backoff import BackoffPolicy
from ..util.metrics import FOLLOWER_EVENTS, FOLLOWER_REDIRECTS
from .entry import Entry
from .meta_log import MetaLogTrimmed


class MetaFollower:
    """Tails a primary filer's metadata stream into a local store.

    `source` is the primary's HTTP address (gRPC derived); tests may
    instead pass `source_log` — an in-process (Durable)MetaLog — which
    skips the wire entirely (the crash/resume property test drives the
    cursor discipline through this seam). `state_path` holds the durable
    resume cursor (shadow-write + rename); "" keeps it memory-only,
    which is only sound when the local store is memory-backed too (both
    reset together on restart)."""

    RECONNECT_POLICY = BackoffPolicy(base=0.2, cap=5.0, attempts=1 << 30)

    def __init__(
        self,
        source: str,
        filer,
        state_path: str,
        client_name: str = "",
        source_log=None,
        head_check_s: float = 0.25,
        clock: Callable[[], float] = time.time,
    ):
        self.source = source
        self.filer = filer
        self.state_path = state_path
        self.client_name = client_name or f"follower:{os.getpid()}"
        self.source_log = source_log
        self.head_check_s = head_check_s
        self._clock = clock
        self.cursor_ns = self._load_cursor()
        self.head_ts_ns = 0
        self.head_checked_at = 0.0  # clock() of the last head probe
        self.connected = False
        self.resync_required = False
        self.trimmed_through = 0
        self.applied = 0
        self.skipped = 0
        self.redirects = 0
        self._stopped = False
        self._stop_event: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._head_task: Optional[asyncio.Task] = None

    # ---------------- durable cursor (the geo replicator discipline) ----------------
    def _load_cursor(self) -> int:
        if not self.state_path:
            return 0
        try:
            with open(self.state_path) as f:
                st = json.load(f)
            if st.get("source") not in ("", None, self.source):
                _log.warning(
                    "follower cursor %s was for source %r, now %r: "
                    "resetting", self.state_path, st.get("source"),
                    self.source,
                )
                return 0
            return int(st.get("since_ns", 0))
        except (OSError, ValueError):
            return 0

    def _ack_cursor(self, ts_ns: int) -> None:
        self.cursor_ns = ts_ns
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"since_ns": ts_ns, "source": self.source}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)

    # ---------------- lifecycle ----------------
    async def start(self) -> None:
        self._stopped = False
        self._stop_event = asyncio.Event()
        self._task = asyncio.ensure_future(self._run())
        self._head_task = asyncio.ensure_future(self._head_loop())

    async def stop(self) -> None:
        self._stopped = True
        if self._stop_event is not None:
            self._stop_event.set()
        for t in (self._task, self._head_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        self._task = self._head_task = None

    # ---------------- staleness disclosure ----------------
    def staleness_bound_s(self) -> float:
        """Upper bound on how stale any answer served RIGHT NOW can be
        (see the module docstring for the two-arm argument)."""
        now = self._clock()
        if self.head_checked_at > 0 and self.cursor_ns >= self.head_ts_ns:
            return max(0.0, now - self.head_checked_at)
        return max(0.0, now - self.cursor_ns / 1e9)

    def gate_read(self, req: dict) -> Optional[dict]:
        """Read-your-writes seam for the serving handlers: a request
        carrying min_ts_ns ahead of the tail cursor gets a counted
        redirect to the primary instead of a possibly-stale answer."""
        min_ts = int(req.get("min_ts_ns", 0))
        if min_ts > self.cursor_ns:
            self.redirects += 1
            FOLLOWER_REDIRECTS.inc()
            return {
                "error": "redirect",
                "primary": self.source,
                "cursor_ns": self.cursor_ns,
                "min_ts_ns": min_ts,
            }
        return None

    def status(self) -> dict:
        return {
            "source": self.source,
            "connected": self.connected,
            "cursor_ns": self.cursor_ns,
            "head_ts_ns": self.head_ts_ns,
            "staleness_bound_s": round(self.staleness_bound_s(), 4),
            "applied": self.applied,
            "skipped": self.skipped,
            "redirects": self.redirects,
            "resync_required": self.resync_required,
            "trimmed_through": self.trimmed_through,
        }

    # ---------------- the head probe ----------------
    async def _head_loop(self) -> None:
        while not self._stopped:
            try:
                await self._check_head()
            except asyncio.CancelledError:
                return
            except Exception:
                pass  # next tick retries; the bound degrades honestly
            await asyncio.sleep(self.head_check_s)

    async def _check_head(self) -> None:
        if self.source_log is not None:
            head = self.source_log.last_ts_ns
        else:
            stub = Stub(grpc_address(self.source), "filer")
            conf = await stub.call(
                "GetFilerConfiguration", {}, timeout=5.0
            )
            head = int(conf.get("last_ts_ns", 0))
        # order matters: stamp the probe time BEFORE publishing the new
        # head — a reader between the two sees an older check time with
        # a newer head, which only WIDENS the disclosed bound
        self.head_checked_at = self._clock()
        self.head_ts_ns = head

    # ---------------- the tail loop ----------------
    async def _run(self) -> None:
        failures = 0
        while not self._stopped and not self.resync_required:
            try:
                await self._tail_once()
                failures = 0
            except asyncio.CancelledError:
                return
            except MetaLogTrimmed as e:
                self._trimmed(e.trimmed_through)
                return
            except Exception as e:
                _log.warning(
                    "meta follower tail of %s: %s (%s)", self.source,
                    e, type(e).__name__,
                )
            self.connected = False
            if self._stopped or self.resync_required:
                return
            delay = self.RECONNECT_POLICY.delay(failures, random)
            failures = min(failures + 1, 16)
            await asyncio.sleep(delay)

    def _trimmed(self, through: int) -> None:
        self.trimmed_through = int(through)
        self.resync_required = True
        _log.error(
            "meta follower of %s REQUIRES RESYNC: cursor %d is behind "
            "primary retention (trimmed through %d)",
            self.source, self.cursor_ns, self.trimmed_through,
        )

    async def _tail_once(self) -> None:
        if self.source_log is not None:
            async for ev in self.source_log.subscribe(
                since_ns=self.cursor_ns,
                stopped=self._stop_event.is_set,
            ):
                self.connected = True
                self._apply(ev.to_dict())
            return
        stub = Stub(grpc_address(self.source), "filer")
        stream = stub.server_stream(
            "SubscribeMetadata",
            {
                "client_name": self.client_name,
                "path_prefix": "/",
                "since_ns": self.cursor_ns,
                "strict_resume": True,
            },
        )
        async for msg in stream:
            if msg.get("error") == "trimmed":
                self._trimmed(msg.get("trimmed_through", 0))
                return
            self.connected = True
            self._apply(msg)

    # ---------------- applying one event ----------------
    def _apply(self, msg: dict) -> None:
        """Direct store application — metadata only, chunks untouched.
        Idempotent per event (upserts overwrite, deletes tolerate
        absence), so the apply-then-ack order makes crash replays
        harmless."""
        ts = int(msg.get("ts_ns", 0))
        if ts <= self.cursor_ns:
            self.skipped += 1
            return
        notif = msg.get("event_notification") or {}
        etype = notif.get("event_type", "")
        old = notif.get("old_entry")
        new = notif.get("new_entry")
        store = self.filer.store
        if etype in ("create", "update") and new:
            store.insert_entry(Entry.from_dict(new))
            FOLLOWER_EVENTS.inc(type="upsert")
            self.applied += 1
        elif etype == "rename" and new:
            store.insert_entry(Entry.from_dict(new))
            if old and old.get("full_path") != new.get("full_path"):
                store.delete_entry(old["full_path"])
            FOLLOWER_EVENTS.inc(type="rename")
            self.applied += 1
        elif etype == "delete" and (old or new):
            path = (old or new).get("full_path", "")
            if path:
                # NEVER delete_chunks: the primary owns the data plane;
                # this mirror only forgets the metadata
                store.delete_folder_children(path)
                store.delete_entry(path)
                FOLLOWER_EVENTS.inc(type="delete")
                self.applied += 1
            else:
                self.skipped += 1
        else:
            self.skipped += 1
        self._ack_cursor(ts)
