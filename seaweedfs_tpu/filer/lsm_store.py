"""LSM-tree filer store: WAL + memtable + sorted segment files + compaction.

Fills the reference's embedded-database store role (leveldb/leveldb2,
ref weed/filer2/filerstore.go:12-31, weed/filer2/leveldb2/) with the same
architecture LevelDB itself uses, built natively: acknowledged mutations
land in a fsynced write-ahead log and an in-memory memtable; when the
memtable fills it flushes to an immutable sorted segment file (keys
in memory, values read from disk on demand); lookups consult memtable
then segments newest-first; deletes are tombstones; when segments pile
up they merge into one (newest wins, tombstones dropped). Directory
listings are range scans over the (dir, name) key order.
"""

from __future__ import annotations

import hashlib
import heapq
import os
import struct
import threading
from typing import Dict, List, Optional, Tuple

import msgpack

from .entry import Entry
from .filer_store import _split as _key  # same (dir, name) rule as every store

_FRAME = struct.Struct("<II")  # key-bytes length, value-bytes length


def path_hash64(d: str, n: str) -> int:
    """Stable 64-bit key for one (dir, name) — how string path keys ride
    the u64-keyed ragged device kernel. blake2b is keyed-collision-free
    enough that a collision is a per-probe host re-check, not a design
    concern; '\\x00' can't appear in either component so the pairing is
    injective."""
    return int.from_bytes(
        hashlib.blake2b(
            (d + "\x00" + n).encode("utf-8"), digest_size=8
        ).digest(),
        "little",
    )


def _group_sorted(it):
    """Group a key-sorted (key, payload) iterator into (key, [payloads])."""
    cur_key = None
    group: list = []
    for key, payload in it:
        if key != cur_key:
            if group:
                yield cur_key, group
            cur_key, group = key, [payload]
        else:
            group.append(payload)
    if group:
        yield cur_key, group


def _fsync_dir(path: str) -> None:
    """Persist directory entries (a rename is only durable once the dir is
    fsynced — without this a crash can lose a just-written segment)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class _Segment:
    """One immutable sorted file: keys + value offsets in memory, values on
    disk. Records are [klen][vlen][key-msgpack][value-msgpack]."""

    def __init__(self, path: str):
        self.path = path
        self.keys: List[Tuple[str, str]] = []
        self._offsets: List[Tuple[int, int]] = []  # (value offset, vlen)
        # ONE sequential read, frames parsed from the buffer: per-frame
        # read()/tell()/seek() syscalls dominated segment-open cost at
        # metadata QPS rates (every flush and merge reopens a segment)
        with open(path, "rb") as f:
            data = f.read()
        pos, end = 0, len(data)
        while pos + _FRAME.size <= end:
            klen, vlen = _FRAME.unpack_from(data, pos)
            pos += _FRAME.size
            key = msgpack.unpackb(data[pos : pos + klen], raw=False)
            pos += klen
            self.keys.append((key[0], key[1]))
            self._offsets.append((pos, vlen))
            pos += vlen
        self._f = open(path, "rb")
        self._arena_seg = None

    def get(self, key: Tuple[str, str]) -> Optional[Tuple[bool, Optional[dict]]]:
        """-> (found, entry_dict_or_None-for-tombstone) or None if absent."""
        import bisect

        i = bisect.bisect_left(self.keys, key)
        if i >= len(self.keys) or self.keys[i] != key:
            return None
        return True, self._value(i)

    def _value(self, i: int) -> Optional[dict]:
        off, vlen = self._offsets[i]
        self._f.seek(off)
        raw = self._f.read(vlen)
        v = msgpack.unpackb(raw, raw=False)
        return v  # None == tombstone

    def scan(self, lo: Tuple[str, str], hi: Tuple[str, str]):
        """Yield (key, entry_dict_or_None) for lo <= key < hi."""
        import bisect

        i = bisect.bisect_left(self.keys, lo)
        while i < len(self.keys) and self.keys[i] < hi:
            yield self.keys[i], self._value(i)
            i += 1

    def items(self) -> list:
        """Every (key, value) pair via ONE sequential file read — the
        merge path's bulk accessor (per-entry seek+read made compaction
        the dominant metadata-write cost)."""
        with open(self.path, "rb") as f:
            data = f.read()
        pos, end, out = 0, len(data), []
        while pos + _FRAME.size <= end:
            klen, vlen = _FRAME.unpack_from(data, pos)
            pos += _FRAME.size
            key = msgpack.unpackb(data[pos : pos + klen], raw=False)
            pos += klen
            val = msgpack.unpackb(data[pos : pos + vlen], raw=False)
            pos += vlen
            out.append(((key[0], key[1]), val))
        return out

    def arena_segment(self):
        """Immutable DeviceColumnArena descriptor: the segment's keys as
        a SORTED u64 hash column, offs carrying the permutation back to
        the original row (so a device hit decodes to `keys[off]` /
        `_value(off)` host-side), sizes all-ones (unused; the kernel's
        column layout wants one). Built once and cached — segments never
        change content. No bloom: filer stores cap at max_segments=4, so
        the pre-filter buys little here."""
        seg = self._arena_seg
        if seg is None:
            import numpy as np

            from ..ops.ragged_lookup import ArenaSegment

            h = np.fromiter(
                (path_hash64(d, n) for d, n in self.keys),
                dtype=np.uint64,
                count=len(self.keys),
            )
            perm = np.argsort(h, kind="stable").astype(np.uint32)
            seg = self._arena_seg = ArenaSegment(
                keys=np.ascontiguousarray(h[perm]),
                offs=perm,
                sizes=np.ones(len(perm), dtype=np.uint32),
                source=self,
                # compaction closes merged-away segments; the arena
                # prunes them at its next refresh
                alive=lambda s=self: not s._f.closed,
            )
        return seg

    def close(self) -> None:
        self._f.close()


def _arena_prefetch_hint(seg: "_Segment") -> None:
    """Offer a newly sealed run to the process's device column arena
    (ISSUE 20 satellite). Strictly best-effort and side-effect-free on
    the store: an arena is never CREATED here (peek, not get), the
    column build is skipped entirely when no arena is live, and any
    arena-side trouble degrades to a counted skip, never a store error.
    Every outcome lands in arena_prefetch_total{result}."""
    try:
        from ..ops.ragged_lookup import peek_default_arena

        arena = peek_default_arena()
        result = "no_arena" if arena is None else arena.prefetch(
            seg.arena_segment()
        )
    except Exception:
        result = "error"
    try:
        from ..util.metrics import ARENA_PREFETCH

        ARENA_PREFETCH.inc(result=result)
    except ImportError:
        pass


def _write_segment(path: str, items: List[Tuple[Tuple[str, str], Optional[dict]]]) -> None:
    packer = msgpack.Packer(use_bin_type=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        for key, value in items:
            kb = packer.pack(list(key))
            vb = packer.pack(value)
            f.write(_FRAME.pack(len(kb), len(vb)))
            f.write(kb)
            f.write(vb)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class LsmFilerStore:
    """FilerStore over a directory of WAL + segment files."""

    def __init__(
        self,
        directory: str,
        memtable_limit: int = 512,
        max_segments: int = 4,
        fsync: bool = True,
    ):
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        # exclusive directory lock: two processes appending the same
        # wal.log / racing MANIFEST rewrites would corrupt the store (the
        # sqlite-backed stores get this from their engine; LevelDB itself
        # uses a LOCK file) — fail fast instead
        self._lock_fd = os.open(
            os.path.join(directory, "LOCK"), os.O_CREAT | os.O_RDWR, 0o644
        )
        try:
            import fcntl

            fcntl.flock(self._lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except (ImportError, AttributeError):
            pass  # non-POSIX: no advisory locking available
        except OSError:
            os.close(self._lock_fd)
            raise RuntimeError(
                f"lsm store directory {directory!r} is locked by another "
                "process"
            )
        self.memtable_limit = memtable_limit
        self.max_segments = max_segments
        self.fsync = fsync
        self.write_rounds = 0  # see MemoryFilerStore.write_rounds
        self._lock = threading.RLock()
        self._mem: Dict[Tuple[str, str], Optional[dict]] = {}
        self._packer = msgpack.Packer(use_bin_type=True)

        # the MANIFEST names the live segments; files it doesn't list are
        # leftovers from an interrupted compaction and are ignored + swept
        # (so a failed old-segment delete can never resurrect entries)
        self._manifest_path = os.path.join(directory, "MANIFEST")
        if os.path.exists(self._manifest_path):
            with open(self._manifest_path) as f:
                seqs = [int(x) for x in f.read().split() if x]
        else:
            seqs = sorted(
                int(fn[4:-4])
                for fn in os.listdir(directory)
                if fn.startswith("seg-") and fn.endswith(".sst")
            )
        self._segments: List[_Segment] = [  # oldest .. newest
            _Segment(os.path.join(directory, f"seg-{seq}.sst"))
            for seq in seqs
        ]
        self._seqs = list(seqs)
        self._next_seq = (max(seqs) + 1) if seqs else 1
        self._sweep_unlisted()

        # WAL replay: mutations acknowledged but not yet flushed
        self._wal_path = os.path.join(directory, "wal.log")
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                for rec in msgpack.Unpacker(f, raw=False):
                    self._mem[(rec["d"], rec["n"])] = rec["e"]
        self._wal = open(self._wal_path, "ab")

    # ---------------- write path ----------------
    def _log(self, key: Tuple[str, str], value: Optional[dict]) -> None:
        self._wal.write(
            self._packer.pack({"d": key[0], "n": key[1], "e": value})
        )
        self._wal.flush()
        if self.fsync:
            os.fsync(self._wal.fileno())
        self._mem[key] = value
        if len(self._mem) >= self.memtable_limit:
            self._flush_memtable()

    def _write_manifest(self) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(str(s) for s in self._seqs))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)
        _fsync_dir(self.dir)

    def _sweep_unlisted(self) -> None:
        listed = {f"seg-{s}.sst" for s in self._seqs}
        for fn in os.listdir(self.dir):
            if fn.startswith("seg-") and fn not in listed:
                try:
                    os.remove(os.path.join(self.dir, fn))
                except OSError:
                    pass

    def _flush_memtable(self) -> None:
        if not self._mem:
            return
        seq = self._next_seq
        path = os.path.join(self.dir, f"seg-{seq}.sst")
        _write_segment(path, sorted(self._mem.items()))
        _fsync_dir(self.dir)  # the segment must survive before the WAL goes
        seg = _Segment(path)
        self._segments.append(seg)
        self._seqs.append(seq)
        self._next_seq += 1
        self._write_manifest()
        self._mem = {}
        self._wal.close()
        self._wal = open(self._wal_path, "wb")  # truncate: flushed == durable
        if len(self._segments) > self.max_segments:
            self._compact()
        else:
            # ISSUE 20 satellite: the device arena learns the sealed run
            # NOW, from the flush path, instead of paying a first-miss
            # ensure+refresh on the next probe batch. Compaction skips
            # the hint — its merged run replaces segments the arena
            # prunes at refresh anyway.
            _arena_prefetch_hint(seg)

    def _compact(self) -> None:
        """Tiered compaction: merge the ADJACENT segment pair with the
        smallest combined key count, repeating until the count fits
        max_segments. The previous merge-everything policy rewrote the
        whole store every (max_segments x memtable_limit) mutations —
        quadratic total I/O over a write-heavy life, which the object
        gateway's PUT path made visible at metadata QPS rates; merging
        the smallest adjacent pair keeps segments geometrically sized so
        each entry is rewritten O(log n) times. Adjacency preserves the
        rank (newest-wins) order; tombstones drop only when a merge
        includes the OLDEST segment (a mid-stack tombstone must keep
        shadowing older copies). Crash-safe via the MANIFEST exactly as
        before: the merged segment becomes live only when the manifest
        points at it, and unlisted leftovers are swept."""
        while len(self._segments) > self.max_segments:
            sizes = [len(s.keys) for s in self._segments]
            lo = min(
                range(len(sizes) - 1), key=lambda j: sizes[j] + sizes[j + 1]
            )
            self._merge_adjacent(lo, lo + 2)

    def _merge_adjacent(self, lo: int, hi: int) -> None:
        merged: Dict[Tuple[str, str], Optional[dict]] = {}
        for seg in self._segments[lo:hi]:  # oldest -> newest overwrites
            merged.update(seg.items())
        items = sorted(merged.items())
        if lo == 0:  # nothing older left to shadow: tombstones drop
            items = [(k, v) for k, v in items if v is not None]
        old = self._segments[lo:hi]
        new_seg = None
        if items:
            seq = self._next_seq
            path = os.path.join(self.dir, f"seg-{seq}.sst")
            _write_segment(path, items)
            _fsync_dir(self.dir)
            new_seg = _Segment(path)
            self._segments[lo:hi] = [new_seg]
            self._seqs[lo:hi] = [seq]
            self._next_seq += 1
        else:
            self._segments[lo:hi] = []
            self._seqs[lo:hi] = []
        self._write_manifest()
        for seg in old:
            seg.close()
        self._sweep_unlisted()
        if new_seg is not None and len(self._segments) <= self.max_segments:
            _arena_prefetch_hint(new_seg)  # the compacted run is sealed too

    # ---------------- FilerStore interface ----------------
    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self.write_rounds += 1
            self._log(_key(entry.full_path), entry.to_dict())

    update_entry = insert_entry

    def insert_many(self, entries: List[Entry]) -> None:
        """Batched upsert: the whole batch's WAL records go out in ONE
        buffered write + flush/fsync (the per-entry path pays a fsync
        each), then land in the memtable together."""
        if not entries:
            return
        with self._lock:
            self.write_rounds += 1
            recs = []
            for entry in entries:
                d, n = _key(entry.full_path)
                recs.append(
                    self._packer.pack({"d": d, "n": n, "e": entry.to_dict()})
                )
            self._wal.write(b"".join(recs))
            self._wal.flush()
            if self.fsync:
                os.fsync(self._wal.fileno())
            for entry in entries:
                self._mem[_key(entry.full_path)] = entry.to_dict()
            if len(self._mem) >= self.memtable_limit:
                self._flush_memtable()

    def find_entry(self, full_path: str) -> Optional[Entry]:
        with self._lock:
            v = self._current(_key(full_path))
        return Entry.from_dict(v) if v is not None else None

    def find_many(self, paths: List[str]) -> Dict[str, Entry]:
        """Batched probe: many keys under ONE lock acquisition (each
        probe is a memtable hit or a few segment bisects) — the
        gate-batched lookup seam."""
        out: Dict[str, Entry] = {}
        with self._lock:
            for p in paths:
                v = self._current(_key(p))
                if v is not None:
                    out[p] = Entry.from_dict(v)
        return out

    def iter_all(self):
        """Every live (directory, name, Entry) in key order — the
        sharded store's rebalance/cleanup bulk accessor (newest-wins
        fold of memtable + segments, tombstones dropped)."""
        with self._lock:
            merged: Dict[Tuple[str, str], Optional[dict]] = {}
            for seg in self._segments:  # oldest -> newest overwrites
                merged.update(seg.items())
            merged.update(self._mem)
            snap = [
                (k[0], k[1], Entry.from_dict(v))
                for k, v in sorted(merged.items())
                if v is not None
            ]
        return iter(snap)

    def delete_entry(self, full_path: str) -> None:
        with self._lock:
            self._log(_key(full_path), None)

    def delete_folder_children(self, full_path: str) -> None:
        prefix = full_path.rstrip("/")
        with self._lock:
            for d, name in self._subtree_keys(prefix):
                self._log((d, name), None)

    def _subtree_keys(self, prefix: str) -> List[Tuple[str, str]]:
        """Every live key whose directory is prefix or below it."""
        out = set()
        deep = prefix + "/"

        def in_scope(d: str) -> bool:
            return d == prefix or d.startswith(deep)

        for key, v in self._mem.items():
            if v is not None and in_scope(key[0]):
                out.add(key)
        for seg in self._segments:
            for key in seg.keys:
                if in_scope(key[0]):
                    out.add(key)
        # drop keys already dead at the current view
        return [
            k
            for k in sorted(out)
            if self._current(k) is not None
        ]

    def arena_view(self, paths: List[str]):
        """One consistent view for a ragged device dispatch (the
        needle map's `arena_view` twin): memtable hits host-side —
        tombstones included, they must shadow the segments — plus the
        current segment set newest-first as arena descriptors, both
        taken under one lock acquisition."""
        with self._lock:
            mem_hits = {}
            for p in paths:
                k = _key(p)
                if k in self._mem:
                    mem_hits[p] = self._mem[k]
            segments = [
                s.arena_segment() for s in reversed(self._segments)
            ]
        return mem_hits, segments

    def arena_decode(self, seg, row: int, path: str):
        """Verify-and-decode one device hit against the segment the
        arena answered from. Returns (ok, value) — ok False on a hash
        collision or a segment compacted away underneath (caller
        re-probes authoritatively); value None == tombstone."""
        src = seg.source
        key = _key(path)
        try:
            with self._lock:
                if src.keys[row] != key:
                    return False, None
                return True, src._value(row)
        except Exception:
            return False, None

    def _current(self, key: Tuple[str, str]) -> Optional[dict]:
        if key in self._mem:
            return self._mem[key]
        for seg in reversed(self._segments):
            hit = seg.get(key)
            if hit is not None:
                return hit[1]
        return None

    def list_directory_entries(
        self, dir_path: str, start_file_name: str, inclusive: bool, limit: int
    ) -> List[Entry]:
        d = dir_path.rstrip("/") or "/"
        # resume the scan AT the pagination cursor (every source bisects to
        # it) and stop as soon as `limit` live names have merged — a page
        # costs O(page), not O(directory)
        lo = (d, start_file_name or "")
        hi = (d + "\x00", "")  # first key of any later directory
        with self._lock:

            def tagged(seg, rank):  # bind rank NOW, not at generation time
                return ((key, (rank, v)) for key, v in seg.scan(lo, hi))

            sources = [
                tagged(seg, rank)
                for rank, seg in enumerate(self._segments)
            ]
            mem_rank = len(self._segments)  # memtable is newest
            sources.append(
                (
                    (key, (mem_rank, self._mem[key]))
                    # range-filter BEFORE sorting: the memtable source
                    # costs O(in-range), not O(memtable log memtable),
                    # per page
                    for key in sorted(
                        k for k in self._mem if lo <= k < hi
                    )
                )
            )
            out: List[Entry] = []
            for key, group in _group_sorted(heapq.merge(*sources)):
                name = key[1]
                if start_file_name:
                    if inclusive and name < start_file_name:
                        continue
                    if not inclusive and name <= start_file_name:
                        continue
                v = max(group)[1]  # highest rank = newest version
                if v is None:
                    continue
                out.append(Entry.from_dict(v))
                if len(out) >= limit:
                    break
            return out


    def close(self) -> None:
        with self._lock:
            self._flush_memtable()
            self._wal.close()
            for seg in self._segments:
                seg.close()
            if self._lock_fd is not None:
                os.close(self._lock_fd)  # releases the flock
                self._lock_fd = None
