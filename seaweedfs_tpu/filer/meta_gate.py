"""Cross-request micro-batching of filer metadata probes.

The `BatchLookupGate` pattern (`server/lookup_gate.py`) applied one
layer up: concurrent filer requests each pay a per-request
`find_entry` — a store lock acquisition, a B-tree/segment probe, an
Entry decode — even when one event-loop wakeup delivered dozens of
them. `MetaLookupGate` pools the paths of one wakeup and flushes them
as ONE columnar `find_many` against the store (which groups by shard
and probes shards in parallel when the store is a
`ShardedFilerStore`), so concurrent metadata probes become batched
data-parallel work instead of per-request dict chasing — the same
batched-ragged formulation as Ragged Paged Attention (arxiv
2604.15464): requests contribute ragged path lists (a GET contributes
one path, an `_ensure_parents` chain contributes its whole ancestor
spine), the flush flattens them into one dense batch, and each caller
gets its slice back.

Batch formation is adaptive, not timed (the lookup gate's measured
lesson): the first probe of a tick schedules the flush with
`call_soon`, so a lone request flushes immediately with zero added
latency and batches grow on their own under load. Duplicate paths in a
flush are single-flighted — N concurrent probes of one hot path cost
one store hit.
"""

from __future__ import annotations

import asyncio
import os
from typing import Optional

# below this many distinct paths the inline find_many is a few µs —
# cheaper than a worker-thread round trip
_EXECUTOR_THRESHOLD = 64


class MetaLookupGate:
    """Coalesces concurrent path probes per event-loop wakeup and
    flushes them through `store.find_many` (falling back to per-path
    `find_entry` on stores without the batched seam).

    arena: a DeviceColumnArena routes each flush's distinct paths —
    hashed to u64 via `lsm_store.path_hash64` — through ONE ragged
    device dispatch over the store's resident segment hash columns
    (ISSUE 18's filer path-spine leg); values decode host-side with a
    collision/compaction verify, and ANY unavailability (cold arena,
    killed arena, non-LSM store, device absent) silently serves the
    host `find_many` instead. identity_check (default: env
    SEAWEEDFS_TPU_ARENA_IDENTITY, on) re-answers from the host and
    serves the host result on disagreement."""

    def __init__(
        self,
        store,
        max_batch: int = 4096,
        arena=None,
        identity_check: Optional[bool] = None,
    ):
        self.store = store
        self.max_batch = max_batch
        self.arena = arena
        if identity_check is None:
            identity_check = (
                os.environ.get("SEAWEEDFS_TPU_ARENA_IDENTITY", "1") != "0"
            )
        self.identity_check = identity_check
        self._pending: list[tuple] = []  # (paths tuple, future)
        self._count = 0
        self._flush_scheduled = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._tasks: set = set()
        self.stats = {
            "probes": 0,
            "batches": 0,
            "largest_batch": 0,
            "dedup_hits": 0,
            "chains": 0,
            "device_batches": 0,
            "host_fallbacks": 0,
            "identity_mismatches": 0,
        }

    def lookup(self, path: str):
        """Awaitable -> Entry | None."""
        fut = self._enqueue((path,))
        return _first(fut)

    def lookup_many(self, paths: list[str]):
        """Ragged batch: one caller's whole path list (an
        `_ensure_parents` ancestor spine, a multi-component resolve)
        rides the flush as one contribution. Awaitable ->
        [Entry | None] aligned with `paths`."""
        self.stats["chains"] += 1
        return self._enqueue(tuple(paths))

    def _enqueue(self, paths: tuple):
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = asyncio.get_event_loop()
        if self._loop is not loop:
            # a different (fresh) event loop: the server was restarted /
            # the gate is being reused (tests, embedded). Rebind cleanly
            # instead of scheduling call_soon on a closed loop forever;
            # futures parked on the previous loop are failed best-effort
            # (usually their awaiters died with that loop, but if it is
            # somehow still alive they must not hang)
            stale, self._pending = self._pending, []
            for _p, fut in stale:
                try:
                    if not fut.done():
                        fut.set_exception(
                            LookupError("meta gate rebound to a new loop")
                        )
                except RuntimeError:
                    pass  # future's loop already closed
            self._count = 0
            self._flush_scheduled = False
            self._loop = loop
        fut = loop.create_future()
        self._pending.append((paths, fut))
        self._count += len(paths)
        if self._count >= self.max_batch:
            self._flush()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            loop.call_soon(self._flush)
        return fut

    def _flush(self) -> None:
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None  # direct synchronous flush (no loop running)
        if running is not None and running is not self._loop:
            # a flush scheduled on a since-replaced loop must not touch
            # (and resolve cross-thread) the NEW loop's pending futures
            return
        self._flush_scheduled = False
        if not self._pending:
            return
        pending, self._pending, self._count = self._pending, [], 0
        distinct: list[str] = []
        seen: set = set()
        total = 0
        for paths, _fut in pending:
            for p in paths:
                total += 1
                if p not in seen:
                    seen.add(p)
                    distinct.append(p)
        self.stats["probes"] += total
        self.stats["batches"] += 1
        self.stats["dedup_hits"] += total - len(distinct)
        if total > self.stats["largest_batch"]:
            self.stats["largest_batch"] = total
        if len(distinct) < _EXECUTOR_THRESHOLD:
            try:
                found = self._find_many(distinct)
            except Exception as e:
                self._resolve_all(pending, None, e)
                return
            self._resolve_all(pending, found, None)
        else:
            t = asyncio.ensure_future(self._run_batch(pending, distinct))
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)

    async def _run_batch(self, pending: list, distinct: list[str]) -> None:
        loop = asyncio.get_event_loop()
        try:
            # worker thread: the sharded store fans sub-batches across
            # shards there (sqlite/LSM release the GIL in the probe), and
            # the event loop keeps serving while the batch runs
            found = await loop.run_in_executor(
                None, self._find_many, distinct
            )
        except Exception as e:
            self._resolve_all(pending, None, e)
            return
        self._resolve_all(pending, found, None)

    def _find_many(self, distinct: list[str]) -> dict:
        if self.arena is not None and distinct:
            found = self._find_many_arena(distinct)
            if found is not None:
                if self.identity_check:
                    host = self._find_many_host(distinct)
                    if host != found:
                        bad = sum(
                            1
                            for p in distinct
                            if host.get(p) != found.get(p)
                        )
                        self.stats["identity_mismatches"] += bad
                        try:
                            from ..util.metrics import (
                                NEEDLE_MAP_DEVICE_IDENTITY_MISMATCH,
                            )

                            NEEDLE_MAP_DEVICE_IDENTITY_MISMATCH.inc(bad)
                        except ImportError:
                            pass
                        return host
                return found
        return self._find_many_host(distinct)

    def _find_many_host(self, distinct: list[str]) -> dict:
        fm = getattr(self.store, "find_many", None)
        if fm is not None:
            return fm(distinct)
        out = {}
        for p in distinct:
            e = self.store.find_entry(p)
            if e is not None:
                out[p] = e
        return out

    def _find_many_arena(self, distinct: list[str]):
        """One ragged device dispatch for the whole flush; None means
        'host-serve this flush' (never an error — the arena is an
        accelerator, not an authority)."""
        view = getattr(self.store, "arena_view", None)
        decode = getattr(self.store, "arena_decode", None)
        if view is None or decode is None:
            self._note_fallback("no_arena_view")
            return None
        import numpy as np

        from .entry import Entry
        from .filer_store import _split
        from .lsm_store import path_hash64

        mem_hits, segments = view(distinct)
        if segments is None:
            self._note_fallback("no_segments")
            return None
        keys = np.fromiter(
            (path_hash64(*_split(p)) for p in distinct),
            dtype=np.uint64,
            count=len(distinct),
        )
        try:
            res = self.arena.probe_groups([(segments, keys)])[0]
        except Exception:
            res = None
        if res is None:
            self._note_fallback("arena_cold")
            return None
        out: dict = {}
        for i, p in enumerate(distinct):
            if p in mem_hits:
                v = mem_hits[p]  # includes tombstones (None)
            elif res["found"][i]:
                ok, v = decode(
                    segments[int(res["rank"][i])],
                    int(res["off"][i]),
                    p,
                )
                if not ok:
                    # hash collision or segment compacted underneath:
                    # authoritative host re-probe for this one path
                    e = self.store.find_entry(p)
                    if e is not None:
                        out[p] = e
                    continue
            else:
                continue  # absent on device == absent (no false negatives)
            if v is not None:
                out[p] = Entry.from_dict(v)
        self.stats["device_batches"] += 1
        return out

    def _note_fallback(self, reason: str) -> None:
        self.stats["host_fallbacks"] += 1
        try:
            from ..util.metrics import NEEDLE_MAP_DEVICE_FALLBACKS

            NEEDLE_MAP_DEVICE_FALLBACKS.inc(reason=reason)
        except ImportError:
            pass

    @staticmethod
    def _resolve_all(pending: list, found, exc) -> None:
        for paths, fut in pending:
            if fut.done():
                continue
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result([found.get(p) for p in paths])

    def close(self) -> None:
        for _paths, fut in self._pending:
            try:
                if not fut.done():
                    fut.set_exception(LookupError("meta gate closed"))
            except RuntimeError:
                pass  # future parked on an already-closed loop
        self._pending = []
        self._count = 0
        self._loop = None


class MetaWriteGate:
    """`MetaLookupGate`'s same-tick coalescing applied to the WRITE
    side (ISSUE 20): concurrent entry upserts of one event-loop wakeup
    pool into ONE `store.insert_many` round — a burst of S3 PUTs costs
    O(wakeups) store round-trips (lock acquisitions, sqlite commits,
    WAL fsyncs) instead of O(objects).

    Batch formation starts like the lookup gate's (first enqueue of a
    tick schedules the flush with `call_soon`, so a lone write flushes
    immediately with zero added latency) and adds an ADAPTIVE
    group-commit linger: when a flush coalesced more than one
    concurrent contribution — the signature of a burst, where gRPC
    delivers roughly one request per loop tick and same-tick
    coalescing alone would degrade to batches of ~1 — the NEXT flush
    is scheduled with `call_later(linger_s)` so in-flight arrivals
    accumulate into one store round (classic WAL group commit).
    Single-caller traffic never sees the linger (a one-contribution
    flush drops straight back to `call_soon`), so the added latency is
    paid exactly when it buys round-trip amortization. Within a flush
    the LAST write to a path wins (same-tick create-then-update
    collapses to its final state) while first-enqueue ORDER is kept,
    so a contribution's parent-spine entries stay ahead of its leaf.

    Per-item error isolation (the ChunkUploadGate discipline): when the
    batched round fails, every contribution retries alone through
    per-entry `insert_entry` — one bad entry fails only its own caller,
    never the whole flush (counted in stats["item_retries"])."""

    def __init__(
        self,
        store,
        max_batch: int = 4096,
        linger_s: Optional[float] = None,
    ):
        self.store = store
        self.max_batch = max_batch
        if linger_s is None:
            linger_s = float(
                os.environ.get(
                    "SEAWEEDFS_TPU_META_WRITE_GATE_LINGER_MS", "5"
                )
            ) / 1000.0
        self.linger_s = linger_s
        self._pending: list[tuple] = []  # (entries tuple, future)
        self._count = 0
        self._flush_scheduled = False
        # contributions in the last flush: >1 means concurrent callers
        # are in flight, so the next flush lingers to group-commit them
        self._last_contribs = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._tasks: set = set()
        self.stats = {
            "writes": 0,
            "batches": 0,
            "largest_batch": 0,
            "coalesced": 0,
            "item_retries": 0,
            "lingered_batches": 0,
        }

    def insert(self, entry):
        """Awaitable -> None once the entry is durably in the store."""
        return self._enqueue((entry,))

    def insert_many(self, entries: list):
        """One caller's ordered entry group (an `_ensure_parents` spine
        + its leaf, a rename's subtree page) rides the flush as one
        contribution. Awaitable -> None."""
        return self._enqueue(tuple(entries))

    def _enqueue(self, entries: tuple):
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = asyncio.get_event_loop()
        if self._loop is not loop:
            # fresh event loop (restart / embedded reuse): rebind, fail
            # futures parked on the replaced loop best-effort — see
            # MetaLookupGate._enqueue
            stale, self._pending = self._pending, []
            for _e, fut in stale:
                try:
                    if not fut.done():
                        fut.set_exception(
                            LookupError("meta gate rebound to a new loop")
                        )
                except RuntimeError:
                    pass
            self._count = 0
            self._flush_scheduled = False
            self._last_contribs = 0
            self._loop = loop
        fut = loop.create_future()
        self._pending.append((entries, fut))
        self._count += len(entries)
        if self._count >= self.max_batch:
            self._flush()
        elif not self._flush_scheduled:
            self._flush_scheduled = True
            if self.linger_s > 0.0 and self._last_contribs > 1:
                self.stats["lingered_batches"] += 1
                loop.call_later(self.linger_s, self._flush)
            else:
                loop.call_soon(self._flush)
        return fut

    def _flush(self) -> None:
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is not None and running is not self._loop:
            return  # flush scheduled on a since-replaced loop
        self._flush_scheduled = False
        if not self._pending:
            return
        pending, self._pending, self._count = self._pending, [], 0
        self._last_contribs = len(pending)
        # last-write-wins per path, first-enqueue order kept (parents
        # enqueue ahead of their leaf within a contribution)
        merged: dict = {}
        total = 0
        for entries, _fut in pending:
            for e in entries:
                total += 1
                merged[e.full_path] = e
        batch = list(merged.values())
        self.stats["writes"] += total
        self.stats["batches"] += 1
        self.stats["coalesced"] += total - len(batch)
        if total > self.stats["largest_batch"]:
            self.stats["largest_batch"] = total
        try:
            from ..util.metrics import (
                META_WRITE_GATE_BATCHES,
                META_WRITE_GATE_WRITES,
            )

            META_WRITE_GATE_BATCHES.inc()
            META_WRITE_GATE_WRITES.inc(total)
        except ImportError:
            pass
        if len(batch) < _EXECUTOR_THRESHOLD:
            errs = self._apply(pending, batch)
            self._resolve(pending, errs)
        else:
            t = asyncio.ensure_future(self._run_batch(pending, batch))
            self._tasks.add(t)
            t.add_done_callback(self._tasks.discard)

    async def _run_batch(self, pending: list, batch: list) -> None:
        loop = asyncio.get_event_loop()
        # worker thread: the batched round fsyncs / commits — the event
        # loop keeps serving while durability happens off-loop; futures
        # resolve back here, on their own loop
        errs = await loop.run_in_executor(None, self._apply, pending, batch)
        self._resolve(pending, errs)

    def _apply(self, pending: list, batch: list):
        """Store rounds only (loop-thread or executor safe). Returns
        None on batched success, else per-contribution exceptions (None
        where the per-item retry succeeded)."""
        try:
            im = getattr(self.store, "insert_many", None)
            if im is not None:
                im(batch)
            else:
                for e in batch:
                    self.store.insert_entry(e)
            return None
        except Exception:
            # isolate: the batch round failed as a unit — retry every
            # contribution alone so one poisoned entry fails only its
            # own caller
            errs = []
            for entries, _fut in pending:
                exc = None
                for e in entries:
                    self.stats["item_retries"] += 1
                    try:
                        self.store.insert_entry(e)
                    except Exception as item_exc:
                        exc = item_exc
                errs.append(exc)
            return errs

    @staticmethod
    def _resolve(pending: list, errs) -> None:
        for i, (_entries, fut) in enumerate(pending):
            if fut.done():
                continue
            exc = errs[i] if errs is not None else None
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(None)

    def close(self) -> None:
        for _entries, fut in self._pending:
            try:
                if not fut.done():
                    fut.set_exception(LookupError("meta gate closed"))
            except RuntimeError:
                pass
        self._pending = []
        self._count = 0
        self._last_contribs = 0
        self._loop = None


async def _first(fut):
    return (await fut)[0]
