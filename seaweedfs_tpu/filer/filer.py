"""Filer core: directory-tree invariants over a FilerStore
(ref: weed/filer2/filer.go:29-42, filer_delete_entry.go)."""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Iterable, Optional

from .entry import Attr, Entry, FileChunk, new_directory_entry
from .filer_store import FilerStore
from .meta_log import MetaLog

# durable ledger of fids referenced by MORE than one entry (S3
# UploadPartCopy's chunk-aligned fast path shares source fids with the
# copied part instead of re-uploading bytes). Stored as a hidden entry in
# the filer store itself so refcounts survive restarts with the entries
# they protect.
FID_REFS_PATH = "/.seaweedfs/fid_refs"


class Filer:
    def __init__(
        self,
        store: FilerStore,
        on_delete_chunks: Optional[Callable] = None,
        notifier=None,
        meta_log: Optional[MetaLog] = None,
    ):
        self.store = store
        self.on_delete_chunks = on_delete_chunks  # async fid-deletion queue hook
        self.notifier = notifier  # notification.Notifier (ref filer_notify.go)
        # meta change log feeding SubscribeMetadata streams + `weed watch`
        # (ref filer.go:38 LocalMetaLogBuffer); callers needing durable
        # history + resumable cursors pass a DurableMetaLog (ISSUE 15)
        self.meta_log = meta_log if meta_log is not None else MetaLog()
        self._fid_refs_cache: Optional[dict[str, int]] = None
        self._fid_refs_lock = threading.Lock()
        root = self.store.find_entry("/")
        if root is None:
            self.store.insert_entry(new_directory_entry("/", 0o775))

    # --- shared-fid refcount ledger (UploadPartCopy chunk referencing) ---
    def _fid_refs(self) -> dict[str, int]:
        """EXTRA references per shared fid (a fid listed by K entries has
        K-1 extra refs); loaded lazily from the durable ledger entry."""
        if self._fid_refs_cache is None:
            refs: dict[str, int] = {}
            e = self.store.find_entry(FID_REFS_PATH)
            if e is not None:
                try:
                    refs = {
                        k: int(v)
                        for k, v in json.loads(
                            e.extended.get("refs", "{}")
                        ).items()
                        if int(v) > 0
                    }
                except (ValueError, TypeError, AttributeError):
                    refs = {}
            self._fid_refs_cache = refs
        return self._fid_refs_cache

    def _save_fid_refs(self) -> None:
        refs = {k: v for k, v in self._fid_refs().items() if v > 0}
        self._fid_refs_cache = refs
        now = time.time()
        self._ensure_parents(FID_REFS_PATH)
        # internal bookkeeping: no meta-log event, no notification
        self.store.insert_entry(
            Entry(
                full_path=FID_REFS_PATH,
                attr=Attr(mtime=now, crtime=now),
                extended={"refs": json.dumps(refs)},
            )
        )

    def add_fid_refs(self, fids: Iterable[str]) -> None:
        """Register one EXTRA reference per listed fid — called BEFORE a
        second entry starts listing a fid it does not own, so a racing
        delete of the original owner can only decrement, never free."""
        fids = [f for f in fids if f]
        if not fids:
            return
        with self._fid_refs_lock:
            refs = self._fid_refs()
            for fid in fids:
                refs[fid] = refs.get(fid, 0) + 1
            self._save_fid_refs()

    def release_fids(self, fids: Iterable[str]) -> None:
        """The single chunk-release gate: every path that used to hand
        fids straight to `on_delete_chunks` routes here. A fid with extra
        references burns one instead of being enqueued for deletion —
        whichever referencing entry dies LAST actually frees the needle."""
        fids = sorted({f for f in fids if f})
        if not fids:
            return
        free: list[str] = []
        with self._fid_refs_lock:
            refs = self._fid_refs()
            changed = False
            for fid in fids:
                if refs.get(fid, 0) > 0:
                    refs[fid] -= 1
                    changed = True
                else:
                    free.append(fid)
            if changed:
                self._save_fid_refs()
        if free and self.on_delete_chunks:
            self.on_delete_chunks(free)

    def _notify(
        self,
        event_type: str,
        path: str,
        entry: Optional[Entry],
        old_entry: Optional[Entry] = None,
    ) -> None:
        entry_dict = entry.to_dict() if entry else None
        old_dict = old_entry.to_dict() if old_entry else None
        directory = path.rsplit("/", 1)[0] or "/"
        from ..notification import EVENT_CREATE, EVENT_DELETE

        if event_type == EVENT_CREATE:
            old_dict = None
        if event_type == EVENT_DELETE:
            old_dict, entry_dict = old_dict or entry_dict, None
        self.meta_log.append(
            directory, event_type, old_entry=old_dict, new_entry=entry_dict
        )
        if self.notifier is not None:
            sink_dict = entry_dict or old_dict
            if event_type == "rename" and old_entry is not None and sink_dict:
                # replication sinks need the source path to drop the old key
                sink_dict = dict(sink_dict)
                sink_dict["_old_path"] = old_entry.full_path
            self.notifier.notify(event_type, path, sink_dict)

    # --- mkdir -p for parents (ref filer.go CreateEntry ensuring dirs) ---
    def _ensure_parents(self, full_path: str) -> None:
        # fast path: when the DIRECT parent already exists as a directory,
        # its own ancestors exist by construction (directories are only
        # ever created through this walk, and deletes remove whole
        # subtrees), so the per-component probe chain collapses to one
        # store lookup — measurable at gateway PUT rates on deep paths
        parent = full_path.rstrip("/").rpartition("/")[0]
        if parent and parent != "/":
            existing = self.store.find_entry(parent)
            if existing is not None:
                if not existing.is_directory:
                    raise NotADirectoryError(f"{parent} is a file")
                return
        parts = [p for p in full_path.split("/") if p][:-1]
        chain: list[str] = []
        path = ""
        for p in parts:
            path += "/" + p
            chain.append(path)
        if not chain:
            return
        # the whole ancestor spine probes as ONE ragged batch (a deep
        # path costs one find_many, not one store round trip per
        # component); stores without the batched seam keep the per-
        # component walk
        find_many = getattr(self.store, "find_many", None)
        found = find_many(chain) if find_many is not None else None
        missing: list[Entry] = []
        for path in chain:
            existing = (
                found.get(path) if found is not None
                else self.store.find_entry(path)
            )
            if existing is None:
                missing.append(new_directory_entry(path))
            elif not existing.is_directory:
                raise NotADirectoryError(f"{path} is a file")
        if missing:
            # the missing spine inserts as ONE batched round too (the
            # write twin of the probe above), root-first by construction
            self._insert_batch(missing)

    def _insert_batch(self, entries: list[Entry]) -> None:
        im = getattr(self.store, "insert_many", None)
        if im is not None:
            im(entries)
        else:
            for e in entries:
                self.store.insert_entry(e)

    def create_entry(self, entry: Entry, exclusive: bool = False) -> None:
        """exclusive=True is the O_EXCL analogue: refuse to replace any
        existing entry (the replace path frees the old file's chunks, so
        directory-creating callers must never race onto a file)."""
        if entry.full_path != "/":
            self._ensure_parents(entry.full_path)
        existing = self.store.find_entry(entry.full_path)
        if exclusive and existing is not None:
            raise FileExistsError(entry.full_path)
        if existing is not None and existing.chunks:
            old_fids = {c.fid for c in existing.chunks} - {
                c.fid for c in entry.chunks
            }
            if old_fids:
                self.release_fids(old_fids)
        self.store.insert_entry(entry)
        from ..notification import EVENT_CREATE, EVENT_UPDATE

        self._notify(
            EVENT_UPDATE if existing is not None else EVENT_CREATE,
            entry.full_path,
            entry,
            old_entry=existing,
        )

    def update_entry(self, entry: Entry) -> None:
        from ..notification import EVENT_UPDATE

        old = self.store.find_entry(entry.full_path)
        self.store.update_entry(entry)
        self._notify(EVENT_UPDATE, entry.full_path, entry, old_entry=old)

    def find_entry(self, full_path: str) -> Optional[Entry]:
        return self.store.find_entry(full_path)

    def delete_entry(
        self, full_path: str, recursive: bool = False, delete_chunks: bool = True
    ) -> list[FileChunk]:
        """Returns the chunks to garbage-collect
        (ref filer_delete_entry.go)."""
        entry = self.store.find_entry(full_path)
        if entry is None:
            return []
        collected: list[FileChunk] = []
        deleted_children: list[Entry] = []
        if entry.is_directory:
            children = self.store.list_directory_entries(full_path, "", True, 2)
            if children and not recursive:
                raise OSError(f"directory {full_path} not empty")
            for child in self.list_entries_recursive(full_path):
                collected.extend(child.chunks)
                deleted_children.append(child)
            self.store.delete_folder_children(full_path)
        else:
            collected.extend(entry.chunks)
        self.store.delete_entry(full_path)
        if delete_chunks and collected:
            self.release_fids({c.fid for c in collected})
        from ..notification import EVENT_DELETE

        # per-child events so deeper-prefix subscribers see their deletions
        # (ref filer_grpc_server_rename.go / filer_delete_entry.go notify
        # per moved/removed entry)
        for child in deleted_children:
            self._notify(EVENT_DELETE, child.full_path, None, old_entry=child)
        self._notify(EVENT_DELETE, full_path, None, old_entry=entry)
        return collected

    def list_entries(
        self,
        dir_path: str,
        start_file_name: str = "",
        inclusive: bool = True,
        limit: int = 1024,
    ) -> list[Entry]:
        return self.store.list_directory_entries(
            dir_path, start_file_name, inclusive, limit
        )

    def list_entries_recursive(self, dir_path: str):
        stack = [dir_path]
        while stack:
            d = stack.pop()
            last = ""
            while True:
                batch = self.store.list_directory_entries(d, last, False, 1024)
                if not batch:
                    break
                for e in batch:
                    yield e
                    if e.is_directory:
                        stack.append(e.full_path)
                last = batch[-1].name

    def rename(self, old_path: str, new_path: str) -> None:
        """Move a file or directory subtree (ref filer_grpc_server_rename.go)."""
        entry = self.store.find_entry(old_path)
        if entry is None:
            raise FileNotFoundError(old_path)
        if new_path == old_path or new_path.startswith(old_path + "/"):
            # moving a directory into its own subtree would insert the
            # moved children and then prefix-delete them with the source
            raise OSError(f"cannot move {old_path} into itself")
        # validate the destination BEFORE any child is moved — failing
        # mid-loop would leave half-migrated metadata behind
        dest = self.store.find_entry(new_path)
        if dest is not None:
            if dest.is_directory:
                raise IsADirectoryError(new_path)
            if entry.is_directory:
                raise NotADirectoryError(new_path)
        self._ensure_parents(new_path)
        from ..notification import EVENT_RENAME

        if entry.is_directory:
            # the whole subtree inserts as ONE batched store round
            # (per-child inserts paid a commit/fsync each); per-child
            # rename events still flow so subscribers see every move
            pairs = [
                (
                    child,
                    Entry(
                        full_path=new_path
                        + child.full_path[len(old_path):],
                        attr=child.attr,
                        chunks=child.chunks,
                        extended=child.extended,
                    ),
                )
                for child in self.list_entries_recursive(old_path)
            ]
            if pairs:
                self._insert_batch([moved for _c, moved in pairs])
            for child, moved in pairs:
                self._notify(
                    EVENT_RENAME, moved.full_path, moved, old_entry=child
                )
            self.store.delete_folder_children(old_path)
        # an overwritten destination FILE must free its chunks (mirror of
        # create_entry's replace path)
        if dest is not None and dest.chunks:
            old_fids = {c.fid for c in dest.chunks} - {
                c.fid for c in entry.chunks
            }
            if old_fids:
                self.release_fids(old_fids)
        entry_new = Entry(
            full_path=new_path,
            attr=entry.attr,
            chunks=entry.chunks,
            extended=entry.extended,
        )
        self.store.insert_entry(entry_new)
        self.store.delete_entry(old_path)
        self._notify(EVENT_RENAME, new_path, entry_new, old_entry=entry)

    def touch(self, full_path: str, mime: str, chunks: list[FileChunk], **attrs) -> Entry:
        now = time.time()
        entry = Entry(
            full_path=full_path,
            attr=Attr(mtime=now, crtime=now, mime=mime, **attrs),
            chunks=chunks,
        )
        self.create_entry(entry)
        return entry

    # --- gate-batched write seam (ISSUE 20) ---
    async def create_entry_gated(
        self,
        entry: Entry,
        write_gate,
        lookup_gate=None,
        exclusive: bool = False,
    ) -> None:
        """`create_entry` with both halves coalesced across concurrent
        callers: the ancestor-spine + existing-entry probe rides the
        lookup gate (one `find_many` per event-loop wakeup) and the
        inserts — missing parents + the leaf — ride the write gate (one
        `insert_many` per wakeup), so a burst of S3 PUTs costs
        O(wakeups) store round-trips instead of O(objects).

        exclusive=True keeps the synchronous path: its probe-then-insert
        must stay one atomic block (the O_EXCL contract), which gate
        batching deliberately gives up."""
        if entry.full_path == "/" or exclusive or write_gate is None:
            self.create_entry(entry, exclusive=exclusive)
            return
        parts = [p for p in entry.full_path.split("/") if p][:-1]
        chain: list[str] = []
        path = ""
        for p in parts:
            path += "/" + p
            chain.append(path)
        probe = chain + [entry.full_path]
        if lookup_gate is not None:
            results = await lookup_gate.lookup_many(probe)
        else:
            find_many = getattr(self.store, "find_many", None)
            if find_many is not None:
                found = find_many(probe)
            else:
                found = {
                    p: e
                    for p in probe
                    if (e := self.store.find_entry(p)) is not None
                }
            results = [found.get(p) for p in probe]
        existing = results[-1]
        batch: list[Entry] = []
        for p, got in zip(chain, results[:-1]):
            if got is None:
                batch.append(new_directory_entry(p))
            elif not got.is_directory:
                raise NotADirectoryError(f"{p} is a file")
        if existing is not None and existing.chunks:
            old_fids = {c.fid for c in existing.chunks} - {
                c.fid for c in entry.chunks
            }
            if old_fids:
                self.release_fids(old_fids)
        batch.append(entry)
        # parents enqueue ahead of the leaf in ONE contribution; the
        # await returns only once the whole group is durably stored
        await write_gate.insert_many(batch)
        from ..notification import EVENT_CREATE, EVENT_UPDATE

        self._notify(
            EVENT_UPDATE if existing is not None else EVENT_CREATE,
            entry.full_path,
            entry,
            old_entry=existing,
        )

    async def touch_gated(
        self,
        full_path: str,
        mime: str,
        chunks: list[FileChunk],
        write_gate,
        lookup_gate=None,
        **attrs,
    ) -> Entry:
        now = time.time()
        entry = Entry(
            full_path=full_path,
            attr=Attr(mtime=now, crtime=now, mime=mime, **attrs),
            chunks=chunks,
        )
        await self.create_entry_gated(
            entry, write_gate, lookup_gate=lookup_gate
        )
        return entry
