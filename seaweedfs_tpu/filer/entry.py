"""Filer entries: attributes + chunk lists (ref: weed/filer2/entry.go)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class FileChunk:
    fid: str
    offset: int
    size: int  # LOGICAL (plaintext) size; the stored needle may be larger
    mtime_ns: int = 0  # modification stamp deciding overwrite precedence
    etag: str = ""
    # per-chunk AES-256-GCM key when the content is encrypted client-side
    # (ref filer.proto FileChunk.cipher_key, upload_content.go:30); empty =
    # plaintext chunk
    cipher_key: bytes = b""

    def to_dict(self) -> dict:
        d = {
            "fid": self.fid,
            "offset": self.offset,
            "size": self.size,
            "mtime_ns": self.mtime_ns,
            "etag": self.etag,
        }
        if self.cipher_key:
            import base64

            d["cipher_key"] = base64.b64encode(self.cipher_key).decode()
        return d

    @staticmethod
    def from_dict(d: dict) -> "FileChunk":
        ck = d.get("cipher_key") or b""
        if isinstance(ck, str):
            import base64

            ck = base64.b64decode(ck)
        return FileChunk(
            fid=d["fid"],
            offset=int(d["offset"]),
            size=int(d["size"]),
            mtime_ns=int(d.get("mtime_ns", 0)),
            etag=d.get("etag", ""),
            cipher_key=bytes(ck),
        )


@dataclass
class Attr:
    mtime: float = 0.0
    crtime: float = 0.0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    replication: str = ""
    collection: str = ""
    ttl_seconds: int = 0

    @property
    def is_directory(self) -> bool:
        return bool(self.mode & 0o40000)


@dataclass
class Entry:
    full_path: str
    attr: Attr = field(default_factory=Attr)
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.full_path.rsplit("/", 1)[-1]

    @property
    def parent(self) -> str:
        parent = self.full_path.rsplit("/", 1)[0]
        return parent or "/"

    @property
    def is_directory(self) -> bool:
        return self.attr.is_directory

    def size(self) -> int:
        from .filechunks import total_size

        return total_size(self.chunks)

    def to_dict(self) -> dict:
        return {
            "full_path": self.full_path,
            "is_directory": self.is_directory,
            "attr": {
                "mtime": self.attr.mtime,
                "crtime": self.attr.crtime,
                "mode": self.attr.mode,
                "uid": self.attr.uid,
                "gid": self.attr.gid,
                "mime": self.attr.mime,
                "replication": self.attr.replication,
                "collection": self.attr.collection,
                "ttl_seconds": self.attr.ttl_seconds,
            },
            "chunks": [c.to_dict() for c in self.chunks],
            "extended": self.extended,
        }

    @staticmethod
    def from_dict(d: dict) -> "Entry":
        a = d.get("attr", {})
        return Entry(
            full_path=d["full_path"],
            attr=Attr(
                mtime=a.get("mtime", 0.0),
                crtime=a.get("crtime", 0.0),
                mode=int(a.get("mode", 0o660)),
                uid=int(a.get("uid", 0)),
                gid=int(a.get("gid", 0)),
                mime=a.get("mime", ""),
                replication=a.get("replication", ""),
                collection=a.get("collection", ""),
                ttl_seconds=int(a.get("ttl_seconds", 0)),
            ),
            chunks=[FileChunk.from_dict(c) for c in d.get("chunks", [])],
            extended=d.get("extended", {}),
        )


def new_directory_entry(path: str, mode: int = 0o770) -> Entry:
    now = time.time()
    return Entry(
        full_path=path,
        attr=Attr(mtime=now, crtime=now, mode=mode | 0o40000),
    )
