"""Filer: a directory/file namespace over the object store
(ref: weed/filer2/). Entries carry chunk lists pointing at needle fids;
stores are pluggable (memory, sqlite standing in for the reference's
leveldb/sql family)."""

from .entry import Attr, Entry, FileChunk
from .filechunks import (
    VisibleInterval,
    non_overlapping_visible_intervals,
    read_from_visible_intervals,
    total_size,
)
from .filer import Filer
from .filer_store import (
    FilerStore,
    LogFilerStore,
    MemoryFilerStore,
    SqliteFilerStore,
)
from .sharded_store import ShardedFilerStore

__all__ = [
    "Attr",
    "Entry",
    "FileChunk",
    "VisibleInterval",
    "non_overlapping_visible_intervals",
    "read_from_visible_intervals",
    "total_size",
    "Filer",
    "FilerStore",
    "LogFilerStore",
    "MemoryFilerStore",
    "SqliteFilerStore",
    "ShardedFilerStore",
]
