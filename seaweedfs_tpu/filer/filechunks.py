"""Chunk visibility: which byte ranges of which chunks are readable.

A file is a list of chunks that may overlap; for overlapping ranges the
chunk with the newest mtime wins (ref: weed/filer2/filechunks.go —
NonOverlappingVisibleIntervals / ReadFromChunks). Implemented as an
event-sweep over chunk boundaries rather than the reference's incremental
merge loop.
"""

from __future__ import annotations

from dataclasses import dataclass

from .entry import FileChunk


def total_size(chunks: list[FileChunk]) -> int:
    return max((c.offset + c.size for c in chunks), default=0)


def etag(chunks: list[FileChunk]) -> str:
    if len(chunks) == 1:
        return chunks[0].etag
    import hashlib

    h = hashlib.md5()
    for c in chunks:
        h.update(c.etag.encode())
    return h.hexdigest()


@dataclass(frozen=True)
class VisibleInterval:
    start: int
    stop: int
    fid: str
    mtime_ns: int
    chunk_offset: int  # start of the owning chunk in the file
    cipher_key: bytes = b""  # owning chunk's content key ('' = plaintext)


def non_overlapping_visible_intervals(
    chunks: list[FileChunk],
) -> list[VisibleInterval]:
    """Newest-wins interval resolution, sorted by start."""
    if not chunks:
        return []
    bounds = sorted(
        {c.offset for c in chunks} | {c.offset + c.size for c in chunks}
    )
    # resolve each elementary segment to its newest covering chunk
    ordered = sorted(chunks, key=lambda c: (c.mtime_ns, c.fid))
    segments: list[VisibleInterval] = []
    for lo, hi in zip(bounds, bounds[1:]):
        winner = None
        for c in reversed(ordered):  # newest first
            if c.offset <= lo and hi <= c.offset + c.size:
                winner = c
                break
        if winner is None:
            continue
        segments.append(
            VisibleInterval(
                lo, hi, winner.fid, winner.mtime_ns, winner.offset,
                winner.cipher_key,
            )
        )
    # merge adjacent segments owned by the same chunk
    merged: list[VisibleInterval] = []
    for seg in segments:
        if (
            merged
            and merged[-1].fid == seg.fid
            and merged[-1].stop == seg.start
            and merged[-1].chunk_offset == seg.chunk_offset
        ):
            merged[-1] = VisibleInterval(
                merged[-1].start,
                seg.stop,
                seg.fid,
                seg.mtime_ns,
                seg.chunk_offset,
                seg.cipher_key,
            )
        else:
            merged.append(seg)
    return merged


@dataclass(frozen=True)
class ChunkView:
    fid: str
    offset_in_chunk: int  # where to start reading inside the chunk blob
    size: int
    logical_offset: int  # position in the file
    cipher_key: bytes = b""


def view_from_visibles(
    visibles: list[VisibleInterval], offset: int, size: int
) -> list[ChunkView]:
    """Chunk reads covering [offset, offset+size) (ref ViewFromVisibleIntervals)."""
    stop = offset + size
    views = []
    for v in visibles:
        if v.stop <= offset or v.start >= stop:
            continue
        lo = max(v.start, offset)
        hi = min(v.stop, stop)
        views.append(
            ChunkView(
                fid=v.fid,
                offset_in_chunk=lo - v.chunk_offset,
                size=hi - lo,
                logical_offset=lo,
                cipher_key=v.cipher_key,
            )
        )
    return views


def read_from_visible_intervals(
    visibles: list[VisibleInterval],
    fetch,  # fetch(fid) -> bytes (whole chunk blob)
    offset: int,
    size: int,
) -> bytes:
    """Assemble [offset, offset+size) from chunk blobs, zero-filling holes."""
    out = bytearray(size)
    for view in view_from_visibles(visibles, offset, size):
        blob = fetch(view.fid)
        piece = blob[view.offset_in_chunk : view.offset_in_chunk + view.size]
        pos = view.logical_offset - offset
        out[pos : pos + len(piece)] = piece
    return bytes(out)
