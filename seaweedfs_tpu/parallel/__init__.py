"""Multi-chip parallelism for the EC compute plane.

The storage-system analogue of dp/sp parallelism (SURVEY.md §2.5): volumes
are the batch dimension (dp), the byte stream inside a stripe is the sequence
dimension (sp), and the 14 output shards are the model-parallel outputs.
Sharding rides `jax.sharding.Mesh` + `shard_map`; encode is elementwise
across bytes so sharding needs no collectives, while distributed verify /
degraded reconstruction use psum / all_gather over ICI.
"""

from .sharded_ec import (
    make_mesh,
    sharded_encode,
    sharded_verify,
    sharded_reconstruct_step,
)
from .sharded_lookup import sharded_bulk_lookup

__all__ = [
    "make_mesh",
    "sharded_encode",
    "sharded_verify",
    "sharded_reconstruct_step",
    "sharded_bulk_lookup",
]
