"""Sharded bulk needle-index lookup over a device mesh.

Probe-parallel layout: the sorted index columns are replicated (a volume's
index fits one chip's HBM) and the probe batch is sharded across EVERY mesh
device (both axes flattened), so P probes run as n_devices independent
branchless searches with zero cross-device communication — the serving-side
scale-out of ops/index_kernel.py's single-chip kernel (ref: the per-request
CompactMap search this all replaces, weed/storage/needle_map/
compact_map.go:145-172).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # moved to the jax namespace in newer releases
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from ..ops.index_kernel import _search_range, _split_u64


@functools.lru_cache(maxsize=32)
def _compiled_body(n: int, steps: int, mesh: Mesh):
    """Jitted shard_map body cached by (table size, step count, mesh):
    rebuilding the closure per call would miss jit's trace cache and pay a
    full XLA compile on every serving request."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(("vol", "blk")), P(("vol", "blk"))),
        out_specs=(
            P(("vol", "blk")),
            P(("vol", "blk")),
            P(("vol", "blk")),
        ),
    )
    def body(khi_g, klo_g, off_g, size_g, phi_l, plo_l):
        # derive the carry init from the sharded input so the fori_loop
        # carry has matching varying axes under shard_map
        lo = (phi_l ^ phi_l).astype(jnp.int32)
        hi = lo + n
        return _search_range(
            steps, khi_g, klo_g, off_g, size_g, phi_l, plo_l, lo, hi
        )

    return jax.jit(body)


def sharded_bulk_lookup(
    keys: np.ndarray,
    offsets: np.ndarray,
    sizes: np.ndarray,
    probes: np.ndarray,
    mesh: Mesh,
):
    """(sorted keys u64[M], offsets u32[M], sizes u32[M], probes u64[P])
    -> (offset_units u32[P], sizes u32[P], found bool[P]).

    Probe batches that don't divide the mesh size are zero-padded and the
    extras stripped from the result.
    """
    n = len(keys)
    n_devices = mesh.devices.size
    probes = np.ascontiguousarray(probes, dtype=np.uint64)
    p = len(probes)
    pad = (-p) % n_devices
    if pad:
        # zero-pad so uneven probe batches shard; extras are stripped below
        probes = np.concatenate(
            [probes, np.zeros(pad, dtype=np.uint64)]
        )
    steps = max(1, int(np.ceil(np.log2(max(n, 1)))) + 1)

    khi, klo = _split_u64(np.ascontiguousarray(keys, dtype=np.uint64))
    phi, plo = _split_u64(probes)

    off, size, found = _compiled_body(n, steps, mesh)(
        jnp.asarray(khi),
        jnp.asarray(klo),
        jnp.asarray(offsets.astype(np.uint32)),
        jnp.asarray(sizes.astype(np.uint32)),
        jnp.asarray(phi),
        jnp.asarray(plo),
    )
    return np.asarray(off)[:p], np.asarray(size)[:p], np.asarray(found)[:p]
