"""Sharded erasure-coding over a device mesh.

Mesh axes:
- "vol": data parallel over volumes/stripes (the batch dimension — encoding
  1000 volumes at once is the north-star workload, BASELINE.json);
- "blk": sequence parallel over the byte stream inside each stripe (the
  long-context analogue — a 30GB volume's stripe does not fit one chip's HBM).

Encode/reconstruct are byte-local, so both axes shard without communication;
cross-device collectives appear in verification (psum of mismatch counts)
and in the degraded-read path (all_gather of survivor rows when shards are
sharded by shard-id, mirroring the reference's parallel remote-shard gather,
ref: weed/storage/store_ec.go:319-373).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # moved to the jax namespace in newer releases
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map

from ..ops.gf256 import gf_matmul_expr, pack_bytes, unpack_bytes


def make_mesh(
    n_devices: int | None = None,
    vol_axis: int | None = None,
    devices=None,
) -> Mesh:
    """2-D (vol, blk) mesh over the available devices.

    `devices` overrides the default-backend device list — pass
    jax.devices("cpu") to build a virtual host mesh regardless of which
    accelerator backend is primary."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    if vol_axis is None:
        # most-square factorization, vol >= blk
        vol_axis = 1
        for f in range(int(np.sqrt(n)), 0, -1):
            if n % f == 0:
                vol_axis = n // f
                break
    blk_axis = n // vol_axis
    mesh_devices = np.asarray(devices).reshape(vol_axis, blk_axis)
    return Mesh(mesh_devices, axis_names=("vol", "blk"))


def _encode_packed(matrix: np.ndarray, packed):
    """packed uint32[C, W] -> parity uint32[R, W]; pure function of one shard."""
    rows = [packed[j] for j in range(matrix.shape[1])]
    return jnp.stack(gf_matmul_expr(matrix, rows))


def _pad_vol(data, vol: int):
    """Zero-pad the volume axis up to a multiple of the mesh's vol axis so
    uneven batches shard; GF(2^8) is linear, so zero stripes encode/verify
    to zero and are simply stripped from the result."""
    v = data.shape[0]
    pad = (-v) % vol
    if pad:
        data = jnp.concatenate(
            [data, jnp.zeros((pad,) + data.shape[1:], dtype=data.dtype)]
        )
    return data, v


def sharded_encode(matrix: np.ndarray, data, mesh: Mesh):
    """data uint8[V, C, N] -> parity uint8[V, R, N], sharded (vol, -, blk).

    N must be divisible by 4 * mesh.shape['blk'] (uint32 packing per device).
    """
    matrix = np.asarray(matrix, dtype=np.uint8)
    v, c, n = data.shape
    blk = mesh.shape["blk"]
    assert n % (4 * blk) == 0, f"N={n} not divisible by {4*blk}"
    data = jnp.asarray(data, dtype=jnp.uint8)
    data, v = _pad_vol(data, mesh.shape["vol"])

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P("vol", None, "blk"),
        out_specs=P("vol", None, "blk"),
    )
    def body(local):  # [v_loc, C, n_loc] uint8
        packed = jax.lax.bitcast_convert_type(
            local.reshape(local.shape[0], c, -1, 4), jnp.uint32
        )
        parity = jax.vmap(lambda p: _encode_packed(matrix, p))(packed)
        return jax.lax.bitcast_convert_type(parity, jnp.uint8).reshape(
            local.shape[0], matrix.shape[0], -1
        )

    return jax.jit(body)(data)[:v]


def sharded_verify(matrix: np.ndarray, shards, mesh: Mesh):
    """shards uint8[V, C+R, N] -> global mismatch count (psum over the mesh)."""
    matrix = np.asarray(matrix, dtype=np.uint8)
    k = matrix.shape[1]
    shards = jnp.asarray(shards, dtype=jnp.uint8)
    shards, _ = _pad_vol(shards, mesh.shape["vol"])

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P("vol", None, "blk"),
        out_specs=P(),
    )
    def body(local):
        c = k
        packed = jax.lax.bitcast_convert_type(
            local.reshape(local.shape[0], local.shape[1], -1, 4), jnp.uint32
        )
        parity = jax.vmap(lambda p: _encode_packed(matrix, p[:c]))(packed)
        mism = jnp.sum((parity != packed[:, c:]).astype(jnp.int32))
        mism = jax.lax.psum(mism, axis_name="vol")
        return jax.lax.psum(mism, axis_name="blk")

    return jax.jit(body)(shards)


def sharded_reconstruct_step(
    dec_rows: np.ndarray, survivors, mesh: Mesh
):
    """Degraded-read analogue: survivor rows sharded across the mesh's "blk"
    axis are locally matmul'd by the (static) decode rows; the "vol" axis
    batches volumes. survivors: uint8[V, k, N] -> uint8[V, len(dec_rows), N].
    """
    dec_rows = np.asarray(dec_rows, dtype=np.uint8)
    survivors = jnp.asarray(survivors, dtype=jnp.uint8)
    k = dec_rows.shape[1]
    survivors, v = _pad_vol(survivors, mesh.shape["vol"])

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=P("vol", None, "blk"),
        out_specs=P("vol", None, "blk"),
    )
    def body(local):
        packed = jax.lax.bitcast_convert_type(
            local.reshape(local.shape[0], k, -1, 4), jnp.uint32
        )
        out = jax.vmap(lambda p: _encode_packed(dec_rows, p))(packed)
        return jax.lax.bitcast_convert_type(out, jnp.uint8).reshape(
            local.shape[0], dec_rows.shape[0], -1
        )

    return jax.jit(body)(survivors)[:v]


def sharded_reconstruct_padded(
    dec_rows: np.ndarray, survivors: np.ndarray, mesh: Mesh
) -> np.ndarray:
    """sharded_reconstruct_step for arbitrary byte widths: pads the column
    axis up to the mesh's packing unit (4 bytes x blk devices — zero columns
    decode to zero under GF linearity) and slices the pad back off. The
    multi-chip leg rebuild_ec_files_multi dispatches survivor batches
    through."""
    survivors = np.ascontiguousarray(survivors, dtype=np.uint8)
    v, k, n = survivors.shape
    unit = 4 * mesh.shape["blk"]
    pad = (-n) % unit
    if pad:
        survivors = np.concatenate(
            [survivors, np.zeros((v, k, pad), dtype=np.uint8)], axis=2
        )
    out = np.asarray(sharded_reconstruct_step(dec_rows, survivors, mesh))
    return out[:, :, :n] if pad else out
