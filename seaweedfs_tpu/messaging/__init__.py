from .broker import MessageBroker, pick_partition

__all__ = ["MessageBroker", "pick_partition"]
