"""Pub/sub message broker (ref: weed/messaging/broker/).

Topics are split into partitions; producers hash a key onto a partition
(ref broker/consistent_distribution.go) and consumers subscribe per
(namespace, topic, partition) with an offset. gRPC service "messaging":
Publish (unary), Subscribe (server stream), GetTopicConfiguration.

Durability mirrors the reference's filer-journaled log buffer
(ref: broker/broker_grpc_server_publish.go + weed/util/log_buffer): when a
filer address is configured, publishes accumulate per partition and a
flusher appends them as msgpack segment files under
/topics/<ns>/<topic>/<partition>/<first_offset>.log through the filer's
HTTP path; on startup the broker replays those segments, so a restart
keeps every flushed message and offset numbering.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from collections import defaultdict
from typing import Optional

import msgpack

from ..pb import grpc_address
from ..pb.rpc import Service, serve

DEFAULT_PARTITIONS = 4
TOPICS_ROOT = "/topics"


def pick_partition(key: bytes, partition_count: int) -> int:
    """Stable key -> partition hash (ref consistent_distribution.go)."""
    if not key:
        return 0
    digest = hashlib.md5(key).digest()
    return int.from_bytes(digest[:4], "big") % partition_count


class _Partition:
    def __init__(self):
        self.messages: list[dict] = []
        self.flushed = 0  # messages[:flushed] are journaled to the filer
        self.new_message = asyncio.Event()


class MessageBroker:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 17777,
        filer: str = "",
        flush_interval: float = 1.0,
    ):
        self.host = host
        self.port = port
        self.address = f"{host}:{port}"
        self.filer = filer
        self.flush_interval = flush_interval
        self._topics: dict[tuple[str, str], list[_Partition]] = {}
        self._configs: dict[tuple[str, str], dict] = {}
        self._grpc_server = None
        self._http = None
        self._flush_task: Optional[asyncio.Task] = None

    @staticmethod
    def _ns(namespace: str) -> str:
        """Canonical namespace: '' and 'default' are the same journal dir,
        so they must be the same topic key too."""
        return namespace or "default"

    def _partitions(self, namespace: str, topic: str) -> list[_Partition]:
        key = (self._ns(namespace), topic)
        if key not in self._topics:
            count = self._configs.get(key, {}).get(
                "partition_count", DEFAULT_PARTITIONS
            )
            self._topics[key] = [_Partition() for _ in range(count)]
        return self._topics[key]

    async def start(self) -> None:
        if self.filer:
            import aiohttp

            from ..util.http_timeouts import client_timeout

            self._http = aiohttp.ClientSession(timeout=client_timeout())
            await self._load_journal()
            self._flush_task = asyncio.ensure_future(self._flush_loop())
        svc = Service("messaging")
        svc.unary("ConfigureTopic")(self._grpc_configure)
        svc.unary("GetTopicConfiguration")(self._grpc_get_configuration)
        svc.unary("Publish")(self._grpc_publish)
        svc.server_stream("Subscribe")(self._grpc_subscribe)
        self._grpc_server = await serve(grpc_address(self.address), svc)

    async def stop(self) -> None:
        if self._flush_task is not None:
            self._flush_task.cancel()
            try:
                await self._flush_task
            except (asyncio.CancelledError, Exception):
                pass
            await self._flush_all()
        if self._grpc_server is not None:
            await self._grpc_server.stop(0.5)
        if self._http is not None:
            await self._http.close()

    # ---------------- filer journal ----------------
    def _partition_dir(self, namespace: str, topic: str, partition: int) -> str:
        return f"{TOPICS_ROOT}/{self._ns(namespace)}/{topic}/{partition}"

    async def _filer_list(self, directory: str) -> list[dict]:
        """Paginated listing — a long-lived partition accumulates far more
        segment files than one listing page."""
        entries: list[dict] = []
        last = ""
        while True:
            url = f"http://{self.filer}{directory}?limit=1000"
            if last:
                url += f"&lastFileName={last}"
            async with self._http.get(
                url, headers={"Accept": "application/json"}
            ) as resp:
                if resp.status != 200:
                    return entries
                body = await resp.json()
                page = body.get("Entries") or []
            entries.extend(page)
            if len(page) < 1000:
                return entries
            last = page[-1]["FullPath"].rsplit("/", 1)[-1]

    async def _load_journal(self) -> None:
        """Replay segment files into memory so offsets continue where the
        previous broker stopped (ref the reference's filer topic dirs)."""
        for ns_entry in await self._filer_list(TOPICS_ROOT):
            ns_path = ns_entry["FullPath"]
            namespace = ns_path.rsplit("/", 1)[-1]
            for topic_entry in await self._filer_list(ns_path):
                topic_path = topic_entry["FullPath"]
                topic = topic_path.rsplit("/", 1)[-1]
                key = (namespace, topic)  # dir names are already canonical
                # topic config rides along as topic.conf
                parts: dict[int, list[dict]] = defaultdict(list)
                for part_entry in await self._filer_list(topic_path):
                    name = part_entry["FullPath"].rsplit("/", 1)[-1]
                    if name == "topic.conf":
                        async with self._http.get(
                            f"http://{self.filer}{part_entry['FullPath']}"
                        ) as resp:
                            if resp.status == 200:
                                import json

                                self._configs[key] = json.loads(await resp.read())
                        continue
                    if not name.isdigit():
                        continue
                    partition = int(name)
                    segments = sorted(
                        e["FullPath"]
                        for e in await self._filer_list(part_entry["FullPath"])
                        if e["FullPath"].endswith(".log")
                    )
                    for seg in segments:
                        async with self._http.get(
                            f"http://{self.filer}{seg}"
                        ) as resp:
                            if resp.status != 200:
                                continue
                            unpacker = msgpack.Unpacker(raw=False)
                            unpacker.feed(await resp.read())
                            for msg in unpacker:
                                parts[partition].append(msg)
                if parts:
                    count = self._configs.get(key, {}).get(
                        "partition_count", max(parts) + 1
                    )
                    plist = [_Partition() for _ in range(max(count, max(parts) + 1))]
                    for idx, msgs in parts.items():
                        plist[idx].messages = msgs
                        plist[idx].flushed = len(msgs)
                    self._topics[key] = plist

    async def _flush_all(self) -> None:
        for (namespace, topic), plist in list(self._topics.items()):
            for idx, p in enumerate(plist):
                await self._flush_partition(namespace, topic, idx, p)

    async def _flush_partition(
        self, namespace: str, topic: str, idx: int, p: _Partition
    ) -> None:
        if self._http is None or p.flushed >= len(p.messages):
            return
        pending = p.messages[p.flushed :]
        body = b"".join(
            msgpack.packb(m, use_bin_type=True) for m in pending
        )
        path = f"{self._partition_dir(namespace, topic, idx)}/{p.flushed:020d}.log"
        try:
            async with self._http.put(
                f"http://{self.filer}{path}", data=body
            ) as resp:
                if resp.status < 300:
                    p.flushed += len(pending)
        except Exception:
            pass  # retried on the next tick

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.flush_interval)
            await self._flush_all()

    # ---------------- RPCs ----------------
    async def _grpc_configure(self, req, context) -> dict:
        key = (self._ns(req.get("namespace", "")), req["topic"])
        self._configs[key] = {
            "partition_count": int(req.get("partition_count", DEFAULT_PARTITIONS))
        }
        if self._http is not None:
            import json

            path = f"{TOPICS_ROOT}/{key[0]}/{req['topic']}/topic.conf"
            try:
                async with self._http.put(
                    f"http://{self.filer}{path}",
                    data=json.dumps(self._configs[key]).encode(),
                ):
                    pass
            except Exception:
                pass
        return {}

    async def _grpc_get_configuration(self, req, context) -> dict:
        key = (self._ns(req.get("namespace", "")), req["topic"])
        return self._configs.get(key, {"partition_count": DEFAULT_PARTITIONS})

    async def _grpc_publish(self, req, context) -> dict:
        namespace = req.get("namespace", "")
        topic = req["topic"]
        partitions = self._partitions(namespace, topic)
        partition = req.get("partition")
        if partition is None:
            partition = pick_partition(
                req.get("key", b"") or b"", len(partitions)
            )
        p = partitions[int(partition)]
        p.messages.append(
            {
                "key": req.get("key", b""),
                "value": req.get("value", b""),
                "headers": req.get("headers", {}),
                "ts_ns": time.time_ns(),
                "offset": len(p.messages),
            }
        )
        p.new_message.set()
        p.new_message = asyncio.Event()
        return {"partition": int(partition), "offset": len(p.messages) - 1}

    async def _grpc_subscribe(self, req, context):
        namespace = req.get("namespace", "")
        topic = req["topic"]
        partition = int(req.get("partition", 0))
        offset = int(req.get("start_offset", 0))
        p = self._partitions(namespace, topic)[partition]
        while True:
            while offset < len(p.messages):
                yield p.messages[offset]
                offset += 1
            event = p.new_message
            try:
                await asyncio.wait_for(event.wait(), timeout=30)
            except asyncio.TimeoutError:
                yield {"keepalive": True}
