"""Pub/sub message broker (ref: weed/messaging/broker/).

Topics are split into partitions; producers hash a key onto a partition
(ref broker/consistent_distribution.go) and consumers subscribe per
(namespace, topic, partition) with an offset. gRPC service "messaging":
Publish (unary), Subscribe (server stream), GetTopicConfiguration.
Messages persist in memory per broker this round (the reference journals to
filer log files — durable storage lands with the log-buffer subsystem).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from collections import defaultdict
from typing import Optional

from ..pb import grpc_address
from ..pb.rpc import Service, serve

DEFAULT_PARTITIONS = 4


def pick_partition(key: bytes, partition_count: int) -> int:
    """Stable key -> partition hash (ref consistent_distribution.go)."""
    if not key:
        return 0
    digest = hashlib.md5(key).digest()
    return int.from_bytes(digest[:4], "big") % partition_count


class _Partition:
    def __init__(self):
        self.messages: list[dict] = []
        self.new_message = asyncio.Event()


class MessageBroker:
    def __init__(self, host: str = "127.0.0.1", port: int = 17777):
        self.host = host
        self.port = port
        self.address = f"{host}:{port}"
        self._topics: dict[tuple[str, str], list[_Partition]] = {}
        self._configs: dict[tuple[str, str], dict] = {}
        self._grpc_server = None

    def _partitions(self, namespace: str, topic: str) -> list[_Partition]:
        key = (namespace, topic)
        if key not in self._topics:
            count = self._configs.get(key, {}).get(
                "partition_count", DEFAULT_PARTITIONS
            )
            self._topics[key] = [_Partition() for _ in range(count)]
        return self._topics[key]

    async def start(self) -> None:
        svc = Service("messaging")
        svc.unary("ConfigureTopic")(self._grpc_configure)
        svc.unary("GetTopicConfiguration")(self._grpc_get_configuration)
        svc.unary("Publish")(self._grpc_publish)
        svc.server_stream("Subscribe")(self._grpc_subscribe)
        self._grpc_server = await serve(grpc_address(self.address), svc)

    async def stop(self) -> None:
        if self._grpc_server is not None:
            await self._grpc_server.stop(0.5)

    # ---------------- RPCs ----------------
    async def _grpc_configure(self, req, context) -> dict:
        key = (req.get("namespace", ""), req["topic"])
        self._configs[key] = {
            "partition_count": int(req.get("partition_count", DEFAULT_PARTITIONS))
        }
        return {}

    async def _grpc_get_configuration(self, req, context) -> dict:
        key = (req.get("namespace", ""), req["topic"])
        return self._configs.get(key, {"partition_count": DEFAULT_PARTITIONS})

    async def _grpc_publish(self, req, context) -> dict:
        namespace = req.get("namespace", "")
        topic = req["topic"]
        partitions = self._partitions(namespace, topic)
        partition = req.get("partition")
        if partition is None:
            partition = pick_partition(
                req.get("key", b"") or b"", len(partitions)
            )
        p = partitions[int(partition)]
        p.messages.append(
            {
                "key": req.get("key", b""),
                "value": req.get("value", b""),
                "headers": req.get("headers", {}),
                "ts_ns": time.time_ns(),
                "offset": len(p.messages),
            }
        )
        p.new_message.set()
        p.new_message = asyncio.Event()
        return {"partition": int(partition), "offset": len(p.messages) - 1}

    async def _grpc_subscribe(self, req, context):
        namespace = req.get("namespace", "")
        topic = req["topic"]
        partition = int(req.get("partition", 0))
        offset = int(req.get("start_offset", 0))
        p = self._partitions(namespace, topic)[partition]
        while True:
            while offset < len(p.messages):
                yield p.messages[offset]
                offset += 1
            event = p.new_message
            try:
                await asyncio.wait_for(event.wait(), timeout=30)
            except asyncio.TimeoutError:
                yield {"keepalive": True}
