"""Overload control plane: priority admission, adaptive concurrency
limits, fast shedding, per-peer circuit breakers, and a pressure signal
that throttles background maintenance.

Nothing in PRs 2/6/7 defends *goodput* when offered load exceeds
capacity: the serving paths are fast, but a 3x-capacity open-loop storm
(ops/loadgen.py can generate one) just grows queues until every request
times out — and retries/hedges amplify the collapse. This module is the
control plane layered over those fast paths:

- **AdmissionGate** (one per ServingCore, so master/volume/filer/S3 all
  inherit it): every fast-tier request is classified into a priority
  class (foreground reads > writes > gateway metadata > maintenance) and
  admitted, queued, or shed BEFORE any work happens. Two mechanisms:

  * a *queue-deadline*: the protocol stamps each request's arrival; a
    request whose wait (event-loop backlog + gate queue) already exceeds
    its class budget is shed instantly — the request was going to blow
    its caller's deadline anyway, so the µs 503 beats the doomed work.
    Lower classes get smaller budgets, so shedding is
    lowest-class-first by construction;
  * an *adaptive concurrency limit* (AdaptiveLimiter): AIMD on observed
    handler latency vs a tracked baseline, the gradient
    concurrency-limiting shape — requests past the limit queue (bounded,
    with per-class depth shares) instead of piling onto the loop.

  Shed responses are a pre-rendered 503 with ``Retry-After`` served in
  microseconds, counted in ``overload_shed_total{class,reason}`` and
  trace-flagged through the flight recorder's tail sampler.

- **CircuitBreaker** (per peer, shared by the HTTP and gRPC clients):
  closed → open on consecutive failures or a high shed rate, half-open
  probes after the open window (or the peer's own Retry-After). An open
  breaker fails calls in microseconds instead of burning a timeout per
  attempt, and tells the read fan-out to stop hedging into a peer that
  is already shedding.

- **Pressure signal**: gates export max(recent-shed, queue-fullness) in
  [0, 1]; `storage/maintenance.py` consults `global_pressure()` so
  scrub/vacuum/repair I/O yields while foreground traffic is being shed
  (the online-EC characterization result — arxiv 1709.05365 — is that
  background coding I/O visibly steals foreground throughput; the
  shared budget already caps the sum, this makes the cap *dynamic*).

Env knobs (all optional; docs/robustness.md "Overload plane"):
``SEAWEEDFS_TPU_ADMIT`` (0 disables admission, default on),
``SEAWEEDFS_TPU_ADMIT_LIMIT`` (initial concurrency limit),
``SEAWEEDFS_TPU_ADMIT_BUDGET_MS`` (foreground-read queue budget; other
classes scale from it), ``SEAWEEDFS_TPU_RETRY_AFTER_S`` (shed hint),
``SEAWEEDFS_TPU_BREAKER`` (0 disables circuit breakers).
"""

from __future__ import annotations

import asyncio
import math
import os
import time
from collections import deque
from itertools import count
from typing import Optional

from .metrics import (
    ADMISSION_LIMIT,
    ADMISSION_QUEUE_DEPTH,
    CIRCUIT_OPEN,
    CIRCUIT_TRANSITIONS,
    OVERLOAD_SHED,
)

# ------------------------------------------------------- priority classes --

CLASS_READ = 0  # foreground reads (GET/HEAD on the data plane)
CLASS_WRITE = 1  # writes (POST/PUT/DELETE)
CLASS_META = 2  # gateway/filer metadata, everything else HTTP
CLASS_MAINT = 3  # maintenance traffic (scrub/vacuum/repair riders)
N_CLASSES = 4
CLASS_NAMES = ("read", "write", "meta", "maint")

_CLASS_BY_METHOD = {
    "GET": CLASS_READ,
    "HEAD": CLASS_READ,
    "POST": CLASS_WRITE,
    "PUT": CLASS_WRITE,
    "DELETE": CLASS_WRITE,
}


def classify_method(method: str) -> int:
    """Default request classifier: reads above writes above the rest.
    Maintenance RPCs ride gRPC (not the HTTP gate) — their throttle is
    the pressure coupling in storage/maintenance.py."""
    return _CLASS_BY_METHOD.get(method, CLASS_META)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ------------------------------------------------------- adaptive limiter --


class AdaptiveLimiter:
    """AIMD concurrency limit driven by observed latency vs a tracked
    baseline (the gradient concurrency-limiting shape, windowed):

    - every `window` samples, compare the window's mean latency against
      `baseline * tolerance`; above it → multiplicative decrease (the
      server is queueing internally), else, if the limit was actually
      the binding constraint this window, additive increase by 1;
    - the baseline tracks the *floor of windowed means* with a slow
      upward drift: it snaps down to any window that averages lower and
      drifts 10%/window toward higher ones, so it converges on the
      uncontended mean service time, survives regime changes (payload
      mix shifts) without locking in a congested measurement, and — the
      reason it is a mean, not a min — a bimodal service mix (µs cache
      hits beside ms disk reads) cannot pin the baseline at the fast
      mode and turn every window into a multiplicative decrease.
    """

    def __init__(
        self,
        initial: Optional[int] = None,
        min_limit: int = 8,
        max_limit: int = 1024,
        tolerance: float = 2.0,
        window: int = 64,
        decrease: float = 0.85,
    ):
        if initial is None:
            initial = int(_env_f("SEAWEEDFS_TPU_ADMIT_LIMIT", 128))
        self.limit = max(min_limit, int(initial))
        self.min_limit = min_limit
        self.max_limit = max_limit
        self.tolerance = tolerance
        self.window = window
        self.decrease = decrease
        self.baseline_s: Optional[float] = None
        self.decreases = 0  # multiplicative backoffs taken (observability)
        self.increases = 0
        self._n = 0
        self._sum = 0.0
        self._min = float("inf")
        self._hi_inflight = 0

    def on_sample(self, latency_s: float, inflight: int) -> None:
        self._n += 1
        self._sum += latency_s
        if latency_s < self._min:
            self._min = latency_s
        if inflight > self._hi_inflight:
            self._hi_inflight = inflight
        if self._n >= self.window:
            self._update()

    def _update(self) -> None:
        win_avg = self._sum / self._n
        saturated = self._hi_inflight >= self.limit - 1
        self._n = 0
        self._sum = 0.0
        self._min = float("inf")
        self._hi_inflight = 0
        b = self.baseline_s
        if b is None:
            self.baseline_s = win_avg
            return
        # track the floor of windowed means: snap down, drift up at
        # 10%/window (a min would let one µs cache hit define 'healthy')
        self.baseline_s = min(win_avg, b + (win_avg - b) * 0.1)
        if win_avg > self.baseline_s * self.tolerance:
            new = max(self.min_limit, int(self.limit * self.decrease))
            if new < self.limit:
                self.limit = new
                self.decreases += 1
        elif saturated and self.limit < self.max_limit:
            self.limit += 1
            self.increases += 1


# ------------------------------------------- admitted-latency histogram --

# log-bucketed (growth sqrt(2), base 1µs, 64 buckets -> ~4300s span):
# every percentile carries <= ~19% relative error, recording is one log
# + one list increment — cheap enough for the admitted fast path, and
# the per-server admitted p50/p99 it yields is the number an operator
# (and the overload bench) actually wants next to shed counts
_LAT_BASE = 1e-6
_LAT_LOG_G = math.log(math.sqrt(2.0))
_LAT_BUCKETS = 64


def latency_percentile(counts: list, p: float) -> float:
    """Seconds at percentile p in [0,100] of a bucket-count list (as
    `AdmissionGate.admitted_counts` snapshots/deltas); 0.0 when empty."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = total * p / 100.0
    seen = 0.0
    for i, c in enumerate(counts):
        seen += c
        if seen >= rank:
            # geometric midpoint of the covering bucket
            return _LAT_BASE * math.exp(_LAT_LOG_G * (i + 0.5))
    return _LAT_BASE * math.exp(_LAT_LOG_G * _LAT_BUCKETS)


# -------------------------------------------------------- admission gate --

# per-class queue-wait budgets (seconds): a request that already waited
# longer than its class budget is shed before doing work. Lower classes
# get smaller budgets — shedding is lowest-class-first by construction.
_BUDGET_SCALE = (1.0, 0.8, 0.6, 0.2)
# per-class share of the bounded gate queue: when the queue is fuller
# than a class's share allows, that class sheds at arrival while higher
# classes may still queue.
_QUEUE_SHARE = (1.0, 0.5, 0.25, 0.1)


class AdmissionGate:
    """Priority admission for one server's fast tier.

    `try_admit(cls, waited_s)` is the synchronous fast path: True =
    admitted (caller MUST pair with `release`), False = shed (caller
    answers 503 immediately), else a Future the caller awaits via
    `wait_queued`. Single-event-loop use only (no locking — ServingCore
    dispatch is the sole caller)."""

    def __init__(
        self,
        server: str,
        limiter: Optional[AdaptiveLimiter] = None,
        read_budget_s: Optional[float] = None,
        max_queue: int = 512,
        retry_after_s: Optional[float] = None,
        clock=time.monotonic,
    ):
        self.server = server
        # per-process unique identity: server NAMES repeat in in-process
        # clusters (three volume servers are all "volume") — the metric
        # series and the shell's cluster-wide merge must tell the gates
        # apart or distinct gates silently collapse into one
        self.gate_id = str(next(_GATE_SEQ))
        self.limiter = limiter or AdaptiveLimiter()
        if read_budget_s is None:
            read_budget_s = _env_f("SEAWEEDFS_TPU_ADMIT_BUDGET_MS", 50.0) / 1e3
        self.set_read_budget(read_budget_s)
        self.max_queue = max_queue
        self.retry_after_s = (
            retry_after_s
            if retry_after_s is not None
            else _env_f("SEAWEEDFS_TPU_RETRY_AFTER_S", 1.0)
        )
        self._clock = clock
        self.inflight = 0
        self.admitted_total = 0
        self.queued = 0
        self._queues: tuple = tuple(deque() for _ in range(N_CLASSES))
        self.shed_total = 0
        self._shed_children: dict = {}
        self.last_shed_t = 0.0
        self._depth_gauge = ADMISSION_QUEUE_DEPTH
        self._limit_gauge = ADMISSION_LIMIT
        self._limit_gauge.set(
            self.limiter.limit, server=server, gate=self.gate_id
        )
        # server-side latency of ADMITTED requests (admission wait +
        # service), log-bucketed — the number "admitted-request p99"
        # honestly means: a saturated open-loop *generator's* own client
        # backlog cannot pollute it
        self.admitted_counts = [0] * _LAT_BUCKETS

    def set_read_budget(self, read_budget_s: float) -> None:
        """Reset the per-class queue-wait budgets from the foreground-read
        budget (benches scale it from a measured baseline p99)."""
        self.queue_budget_s = tuple(
            read_budget_s * s for s in _BUDGET_SCALE
        )

    # -- admission --
    def try_admit(self, cls: int, waited_s: float = 0.0):
        if waited_s > self.queue_budget_s[cls]:
            self._shed(cls, "deadline")
            return False
        if self.inflight < self.limiter.limit:
            self.inflight += 1
            self.admitted_total += 1
            return True
        if self.queued >= self.max_queue * _QUEUE_SHARE[cls]:
            self._shed(cls, "queue_full")
            return False
        fut = asyncio.get_event_loop().create_future()
        self._queues[cls].append(fut)
        self.queued += 1
        self._depth_gauge.set(
            self.queued, server=self.server, gate=self.gate_id
        )
        return fut

    async def wait_queued(self, cls: int, fut, waited_s: float = 0.0) -> bool:
        """Await a queued admission inside the class's remaining budget;
        past it the request sheds (reason=deadline)."""
        left = max(self.queue_budget_s[cls] - waited_s, 0.001)
        try:
            await asyncio.wait_for(fut, left)
        except asyncio.TimeoutError:
            # wait_for cancelled the future; _wake skips cancelled
            # entries lazily — only the live count must drop NOW
            self.queued -= 1
            self._depth_gauge.set(
                self.queued, server=self.server, gate=self.gate_id
            )
            self._shed(cls, "deadline")
            return False
        except asyncio.CancelledError:
            # the caller's task died while queued (client disconnect mid
            # overload — the exact regime this gate exists for). Undo the
            # bookkeeping or the gate leaks: if _wake granted the slot in
            # the race window before our cancellation landed, hand the
            # inflight slot back (release() will never run for us);
            # otherwise the future is a husk — stop counting it toward
            # the queue depth, same as the timeout path.
            if fut.done() and not fut.cancelled():
                self.inflight -= 1
                self._wake()
            else:
                fut.cancel()
                self.queued -= 1
                self._depth_gauge.set(
                    self.queued, server=self.server, gate=self.gate_id
                )
            raise
        return True

    async def admit(self, cls: int, waited_s: float = 0.0) -> bool:
        r = self.try_admit(cls, waited_s)
        if r is True or r is False:
            return r
        return await self.wait_queued(cls, r, waited_s)

    def release(
        self,
        latency_s: Optional[float] = None,
        total_s: Optional[float] = None,
    ) -> None:
        """`latency_s` is the handler service wall (feeds the AIMD
        limiter), `total_s` the full server-side latency since parse
        completion (wait + service — feeds the admitted histogram)."""
        self.inflight -= 1
        if latency_s is not None:
            lim = self.limiter
            before = lim.limit
            lim.on_sample(latency_s, self.inflight + 1)
            if lim.limit != before:
                self._limit_gauge.set(
                    lim.limit, server=self.server, gate=self.gate_id
                )
        if total_s is not None:
            if total_s < _LAT_BASE:
                i = 0
            else:
                i = min(
                    int(math.log(total_s / _LAT_BASE) / _LAT_LOG_G),
                    _LAT_BUCKETS - 1,
                )
            self.admitted_counts[i] += 1
        self._wake()

    def _wake(self) -> None:
        """Hand freed slots to queued waiters, highest class first."""
        while self.inflight < self.limiter.limit and self.queued:
            fut = None
            for q in self._queues:  # class 0 (reads) first
                while q:
                    f = q.popleft()
                    if not f.done():  # done == cancelled by wait_queued
                        fut = f
                        break
                if fut is not None:
                    break
            if fut is None:
                return  # only cancelled husks remained
            self.queued -= 1
            self._depth_gauge.set(
                self.queued, server=self.server, gate=self.gate_id
            )
            self.inflight += 1
            self.admitted_total += 1
            fut.set_result(True)

    # -- shedding / pressure --
    def _shed(self, cls: int, reason: str) -> None:
        self.shed_total += 1
        self.last_shed_t = self._clock()
        key = (cls, reason)
        child = self._shed_children.get(key)
        if child is None:
            child = self._shed_children[key] = OVERLOAD_SHED.child(
                server=self.server,
                gate=self.gate_id,
                reason=reason,
                **{"class": CLASS_NAMES[cls]},
            )
        child.inc()

    def pressure(self) -> float:
        """Foreground pressure in [0, 1]: 1.0 while shedding (within the
        last second), else queue fullness."""
        if self._clock() - self.last_shed_t < 1.0:
            return 1.0
        if self.queued:
            return min(1.0, self.queued / self.max_queue)
        return 0.0

    def stats(self) -> dict:
        lim = self.limiter
        return {
            "server": self.server,
            "gate": self.gate_id,
            "limit": lim.limit,
            "baseline_ms": (
                round(lim.baseline_s * 1e3, 3)
                if lim.baseline_s is not None
                else None
            ),
            "limit_decreases": lim.decreases,
            "limit_increases": lim.increases,
            "inflight": self.inflight,
            "queued": self.queued,
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "queue_budget_ms": [
                round(b * 1e3, 1) for b in self.queue_budget_s
            ],
            "admitted_p50_ms": round(
                latency_percentile(self.admitted_counts, 50) * 1e3, 3
            ),
            "admitted_p99_ms": round(
                latency_percentile(self.admitted_counts, 99) * 1e3, 3
            ),
            "pressure": round(self.pressure(), 3),
        }


# ------------------------------------------------- gate registry/pressure --

_GATES: list = []
_GATE_SEQ = count(1)  # per-process unique gate ids (names repeat)


def admission_enabled() -> bool:
    return (os.environ.get("SEAWEEDFS_TPU_ADMIT", "1") or "1") not in (
        "0",
        "",
    )


def new_server_gate(server: str) -> Optional[AdmissionGate]:
    """An AdmissionGate for one ServingCore, registered into the global
    pressure signal — or None when admission is disabled by env."""
    if not admission_enabled():
        return None
    gate = AdmissionGate(server)
    _GATES.append(gate)
    return gate


def drop_gate(gate: Optional[AdmissionGate]) -> None:
    """Unregister a stopped server's gate so its last-shed window cannot
    keep pressuring maintenance after the server is gone."""
    if gate is not None:
        try:
            _GATES.remove(gate)
        except ValueError:
            pass


def global_pressure() -> float:
    """Max pressure over every live gate in this process — the signal
    storage/maintenance.py consults. Plain float reads: safe from worker
    threads."""
    p = 0.0
    for g in _GATES:
        gp = g.pressure()
        if gp > p:
            p = gp
            if p >= 1.0:
                break
    return p


def gate_stats() -> list:
    return [g.stats() for g in _GATES]


# ------------------------------------------------------- circuit breaker --

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitOpenError(ConnectionError):
    """Fast-fail for calls to a peer whose breaker is open. A
    ConnectionError on purpose: every existing retry/hedge/failover path
    already treats it as 'peer unavailable' and moves on."""


class CircuitBreaker:
    """Per-peer closed/open/half-open breaker.

    Opens on `fail_threshold` consecutive failures, or when at least
    half of the last `shed_window` outcomes were sheds (503 +
    Retry-After: the peer is alive but actively load-shedding — keep
    hammering it and you ARE the overload). Half-open admits one probe
    after the open window; the probe's outcome closes or re-opens. The
    probe slot leases for `probe_timeout_s`: a probe whose caller never
    reports (cancelled mid-flight, caller died) is reclaimed after the
    lease instead of wedging allow() shut until process restart."""

    def __init__(
        self,
        peer: str,
        fail_threshold: int = 6,
        shed_window: int = 20,
        shed_trip: float = 0.5,
        open_s: float = 0.25,
        probe_timeout_s: float = 5.0,
        clock=time.monotonic,
    ):
        self.peer = peer
        self.fail_threshold = fail_threshold
        self.shed_trip = shed_trip
        self.open_s = open_s
        self.probe_timeout_s = probe_timeout_s
        self._clock = clock
        self.state = CLOSED
        self.opens = 0  # times tripped
        self._consec_fail = 0
        self._ring: deque = deque(maxlen=shed_window)  # True = shed
        self._open_until = 0.0
        self._probe_out = False
        self._probe_deadline = 0.0
        self._last_shed_t = 0.0

    # -- gate --
    def allow(self) -> bool:
        """May a request go to this peer now? Consumes the half-open
        probe slot, so callers must report the outcome via record_*
        (record_cancelled when the request is abandoned outcome-less)."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() < self._open_until:
                return False
            self._transition(HALF_OPEN)
            return self._lease_probe()
        if self._probe_out and self._clock() < self._probe_deadline:
            return False  # half-open: one probe at a time
        # no probe out — or the in-flight probe outlived its lease
        # without reporting: reclaim the slot rather than refuse forever
        return self._lease_probe()

    def _lease_probe(self) -> bool:
        self._probe_out = True
        self._probe_deadline = self._clock() + self.probe_timeout_s
        return True

    def blocked(self) -> bool:
        """Non-consuming peek: would allow() refuse right now? (Replica
        ordering uses this so peeking never eats the half-open probe.)"""
        if self.state == CLOSED:
            return False
        if self.state == OPEN:
            return self._clock() < self._open_until
        return self._probe_out and self._clock() < self._probe_deadline

    def shedding(self) -> bool:
        """Is the peer actively load-shedding? True within ~1s of a shed
        answer — the read fan-out pauses hedging into such a pool (a
        hedge into a shedding peer is pure retry-storm fuel)."""
        return self._clock() - self._last_shed_t < 1.0

    # -- outcomes --
    def record_success(self) -> None:
        self._consec_fail = 0
        self._ring.append(False)
        self._probe_out = False
        if self.state != CLOSED:
            self._transition(CLOSED)

    def record_failure(self) -> None:
        self._consec_fail += 1
        self._ring.append(False)
        if self.state == HALF_OPEN:
            self._probe_out = False
            self._trip(self.open_s)  # failed probe: back to open
        elif self.state == CLOSED and (
            self._consec_fail >= self.fail_threshold
        ):
            self._trip(self.open_s)

    def record_cancelled(self) -> None:
        """The caller abandoned its request before an outcome was known
        (hedged reads losing their race are cancelled routinely). Says
        nothing about the peer's health — but if the request held the
        half-open probe slot it MUST be returned here, or allow()
        refuses the peer until the probe lease expires."""
        if self.state == HALF_OPEN:
            self._probe_out = False

    def record_shed(self, retry_after_s: Optional[float] = None) -> None:
        """A 503/429 shed answer (alive peer refusing load). Not a
        failure for the consecutive count — but a shed-heavy window
        trips the breaker for the peer's own Retry-After hint."""
        self._ring.append(True)
        self._last_shed_t = self._clock()
        if self.state == HALF_OPEN:
            self._probe_out = False
            self._trip(retry_after_s or self.open_s)
            return
        ring = self._ring
        if (
            self.state == CLOSED
            and len(ring) >= ring.maxlen // 2
            and sum(ring) >= len(ring) * self.shed_trip
        ):
            self._trip(retry_after_s or self.open_s)

    def _trip(self, open_for: float) -> None:
        self._transition(OPEN)
        self._open_until = self._clock() + open_for
        self.opens += 1
        self._consec_fail = 0
        self._ring.clear()

    def _transition(self, to: str) -> None:
        if to == self.state:
            return
        self.state = to
        CIRCUIT_TRANSITIONS.inc(peer=self.peer, to=to)
        CIRCUIT_OPEN.set(1.0 if to == OPEN else 0.0, peer=self.peer)


class BreakerRegistry:
    """Process-wide per-peer breakers, shared by the HTTP data-plane
    client and the gRPC stub so both views of one peer's health feed one
    breaker."""

    def __init__(self, **breaker_kwargs):
        self._kw = breaker_kwargs
        self._by_peer: dict[str, CircuitBreaker] = {}

    def get(self, peer: str) -> CircuitBreaker:
        br = self._by_peer.get(peer)
        if br is None:
            br = self._by_peer[peer] = CircuitBreaker(peer, **self._kw)
        return br

    def peek(self, peer: str) -> Optional[CircuitBreaker]:
        return self._by_peer.get(peer)

    def reset(self) -> None:
        self._by_peer.clear()

    def stats(self) -> dict:
        return {
            p: {"state": b.state, "opens": b.opens}
            for p, b in self._by_peer.items()
        }


BREAKERS = BreakerRegistry()


def breakers_enabled() -> bool:
    return (os.environ.get("SEAWEEDFS_TPU_BREAKER", "1") or "1") not in (
        "0",
        "",
    )


def peer_breaker(peer: str) -> Optional[CircuitBreaker]:
    """The shared breaker for a peer address, or None when breakers are
    disabled (env) — callers do `br is None or br.allow()`."""
    if not breakers_enabled():
        return None
    return BREAKERS.get(peer)
