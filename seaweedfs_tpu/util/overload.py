"""Overload control plane: priority admission, adaptive concurrency
limits, fast shedding, per-peer circuit breakers, and a pressure signal
that throttles background maintenance.

Nothing in PRs 2/6/7 defends *goodput* when offered load exceeds
capacity: the serving paths are fast, but a 3x-capacity open-loop storm
(ops/loadgen.py can generate one) just grows queues until every request
times out — and retries/hedges amplify the collapse. This module is the
control plane layered over those fast paths:

- **AdmissionGate** (one per ServingCore, so master/volume/filer/S3 all
  inherit it): every fast-tier request is classified into a priority
  class (foreground reads > writes > gateway metadata > maintenance) and
  admitted, queued, or shed BEFORE any work happens. Two mechanisms:

  * a *queue-deadline*: the protocol stamps each request's arrival; a
    request whose wait (event-loop backlog + gate queue) already exceeds
    its class budget is shed instantly — the request was going to blow
    its caller's deadline anyway, so the µs 503 beats the doomed work.
    Lower classes get smaller budgets, so shedding is
    lowest-class-first by construction;
  * an *adaptive concurrency limit* (AdaptiveLimiter): AIMD on observed
    handler latency vs a tracked baseline, the gradient
    concurrency-limiting shape — requests past the limit queue (bounded,
    with per-class depth shares) instead of piling onto the loop.

  Shed responses are a pre-rendered 503 with ``Retry-After`` served in
  microseconds, counted in ``overload_shed_total{class,reason}`` and
  trace-flagged through the flight recorder's tail sampler.

- **CircuitBreaker** (per peer, shared by the HTTP and gRPC clients):
  closed → open on consecutive failures or a high shed rate, half-open
  probes after the open window (or the peer's own Retry-After). An open
  breaker fails calls in microseconds instead of burning a timeout per
  attempt, and tells the read fan-out to stop hedging into a peer that
  is already shedding.

- **Pressure signal**: gates export max(recent-shed, queue-fullness) in
  [0, 1]; `storage/maintenance.py` consults `global_pressure()` so
  scrub/vacuum/repair I/O yields while foreground traffic is being shed
  (the online-EC characterization result — arxiv 1709.05365 — is that
  background coding I/O visibly steals foreground throughput; the
  shared budget already caps the sum, this makes the cap *dynamic*).

Env knobs (all optional; docs/robustness.md "Overload plane"):
``SEAWEEDFS_TPU_ADMIT`` (0 disables admission, default on),
``SEAWEEDFS_TPU_ADMIT_LIMIT`` (initial concurrency limit),
``SEAWEEDFS_TPU_ADMIT_BUDGET_MS`` (foreground-read queue budget; other
classes scale from it), ``SEAWEEDFS_TPU_RETRY_AFTER_S`` (shed hint),
``SEAWEEDFS_TPU_BREAKER`` (0 disables circuit breakers).
"""

from __future__ import annotations

import asyncio
import math
import os
import time
from collections import deque
from itertools import count
from typing import Optional

from . import tenancy
from .metrics import (
    ADMISSION_LIMIT,
    ADMISSION_QUEUE_DEPTH,
    CIRCUIT_OPEN,
    CIRCUIT_TRANSITIONS,
    OVERLOAD_SHED,
    TENANT_ADMITTED,
    TENANT_ADMITTED_SECONDS,
    TENANT_QUEUE_DEPTH,
)

_DEFAULT_TENANT = tenancy.DEFAULT_TENANT
_POLICY_NOTE = tenancy.note_heat  # heat feed for the top-K label policy

# ------------------------------------------------------- priority classes --

CLASS_READ = 0  # foreground reads (GET/HEAD on the data plane)
CLASS_WRITE = 1  # writes (POST/PUT/DELETE)
CLASS_META = 2  # gateway/filer metadata, everything else HTTP
CLASS_MAINT = 3  # maintenance traffic (scrub/vacuum/repair riders)
N_CLASSES = 4
CLASS_NAMES = ("read", "write", "meta", "maint")

_CLASS_BY_METHOD = {
    "GET": CLASS_READ,
    "HEAD": CLASS_READ,
    "POST": CLASS_WRITE,
    "PUT": CLASS_WRITE,
    "DELETE": CLASS_WRITE,
}


def classify_method(method: str) -> int:
    """Default request classifier: reads above writes above the rest.
    Maintenance RPCs ride gRPC (not the HTTP gate) — their throttle is
    the pressure coupling in storage/maintenance.py."""
    return _CLASS_BY_METHOD.get(method, CLASS_META)


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# ------------------------------------------------------- adaptive limiter --


class AdaptiveLimiter:
    """AIMD concurrency limit driven by observed latency vs a tracked
    baseline (the gradient concurrency-limiting shape, windowed):

    - every `window` samples, compare the window's mean latency against
      `baseline * tolerance`; above it → multiplicative decrease (the
      server is queueing internally), else, if the limit was actually
      the binding constraint this window, additive increase by 1;
    - the baseline tracks the *floor of windowed means* with a slow
      upward drift: it snaps down to any window that averages lower and
      drifts 10%/window toward higher ones, so it converges on the
      uncontended mean service time, survives regime changes (payload
      mix shifts) without locking in a congested measurement, and — the
      reason it is a mean, not a min — a bimodal service mix (µs cache
      hits beside ms disk reads) cannot pin the baseline at the fast
      mode and turn every window into a multiplicative decrease.
    """

    def __init__(
        self,
        initial: Optional[int] = None,
        min_limit: int = 8,
        max_limit: int = 1024,
        tolerance: float = 2.0,
        window: int = 64,
        decrease: float = 0.85,
    ):
        if initial is None:
            initial = int(_env_f("SEAWEEDFS_TPU_ADMIT_LIMIT", 128))
        self.limit = max(min_limit, int(initial))
        self.min_limit = min_limit
        self.max_limit = max_limit
        self.tolerance = tolerance
        self.window = window
        self.decrease = decrease
        self.baseline_s: Optional[float] = None
        self.decreases = 0  # multiplicative backoffs taken (observability)
        self.increases = 0
        self._n = 0
        self._sum = 0.0
        self._min = float("inf")
        self._hi_inflight = 0

    def on_sample(self, latency_s: float, inflight: int) -> None:
        self._n += 1
        self._sum += latency_s
        if latency_s < self._min:
            self._min = latency_s
        if inflight > self._hi_inflight:
            self._hi_inflight = inflight
        if self._n >= self.window:
            self._update()

    def _update(self) -> None:
        win_avg = self._sum / self._n
        saturated = self._hi_inflight >= self.limit - 1
        self._n = 0
        self._sum = 0.0
        self._min = float("inf")
        self._hi_inflight = 0
        b = self.baseline_s
        if b is None:
            self.baseline_s = win_avg
            return
        # track the floor of windowed means: snap down, drift up at
        # 10%/window (a min would let one µs cache hit define 'healthy')
        self.baseline_s = min(win_avg, b + (win_avg - b) * 0.1)
        if win_avg > self.baseline_s * self.tolerance:
            new = max(self.min_limit, int(self.limit * self.decrease))
            if new < self.limit:
                self.limit = new
                self.decreases += 1
        elif saturated and self.limit < self.max_limit:
            self.limit += 1
            self.increases += 1


# ------------------------------------------- admitted-latency histogram --

# log-bucketed (growth sqrt(2), base 1µs, 64 buckets -> ~4300s span):
# every percentile carries <= ~19% relative error, recording is one log
# + one list increment — cheap enough for the admitted fast path, and
# the per-server admitted p50/p99 it yields is the number an operator
# (and the overload bench) actually wants next to shed counts
_LAT_BASE = 1e-6
_LAT_LOG_G = math.log(math.sqrt(2.0))
_LAT_BUCKETS = 64


def latency_percentile(counts: list, p: float) -> float:
    """Seconds at percentile p in [0,100] of a bucket-count list (as
    `AdmissionGate.admitted_counts` snapshots/deltas); 0.0 when empty.

    Interpolates geometrically WITHIN the covering bucket by rank
    fraction: the raw bucket midpoint quantizes every answer to a
    sqrt(2) grid, which turns a p99 RATIO of two such numbers into
    steps of 1.41x — too coarse for the fairness leg's <= 2x
    acceptance bound (2.828 = sqrt(2)^3 is a three-bucket gap, wherever
    the truth lies between 2.0 and 4.0)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    rank = total * p / 100.0
    seen = 0.0
    for i, c in enumerate(counts):
        if c and seen + c >= rank:
            frac = (rank - seen) / c
            return _LAT_BASE * math.exp(_LAT_LOG_G * (i + frac))
        seen += c
    return _LAT_BASE * math.exp(_LAT_LOG_G * _LAT_BUCKETS)


# -------------------------------------------------------- admission gate --

# per-class queue-wait budgets (seconds): a request that already waited
# longer than its class budget is shed before doing work. Lower classes
# get smaller budgets — shedding is lowest-class-first by construction.
_BUDGET_SCALE = (1.0, 0.8, 0.6, 0.2)
# per-class share of the bounded gate queue: when the queue is fuller
# than a class's share allows, that class sheds at arrival while higher
# classes may still queue.
_QUEUE_SHARE = (1.0, 0.5, 0.25, 0.1)


class _TenantState:
    """Per-tenant bookkeeping inside one gate: DRR weight, quota
    buckets, and counters. Metric children are bound per LABEL (not per
    tenant) at the gate level, because the bounded label policy can
    re-map a tenant to 'other' over its lifetime."""

    __slots__ = (
        "name", "weight", "quota", "admitted", "shed", "queued",
        "inflight", "admitted_counts", "pinned", "t_seen", "pub_label",
        "pub_queued",
    )

    def __init__(self, name: str, weight: float, quota):
        self.name = name
        self.weight = weight
        self.quota = quota
        self.admitted = 0
        self.shed = 0
        self.queued = 0
        self.inflight = 0  # admitted, release() not yet seen
        # operator-installed quota/weight (set_tenant_*): never evicted
        self.pinned = False
        self.t_seen = 0.0
        # the label this state's queued count is currently published
        # under, and the amount — per-LABEL depth gauges are aggregated
        # incrementally (many cold tenants share 'other'; last-writer-
        # wins per tenant would under-report and zero out real backlog)
        self.pub_label = None
        self.pub_queued = 0
        # per-tenant twin of AdmissionGate.admitted_counts (log-bucketed
        # server-side wait+service): the fairness bench judges tenant
        # isolation on THESE — a saturated open-loop generator's own
        # client backlog rides the RTT numbers, not the server's
        self.admitted_counts = [0] * _LAT_BUCKETS


class AdmissionGate:
    """Priority admission for one server's fast tier.

    `try_admit(cls, waited_s)` is the synchronous fast path: True =
    admitted (caller MUST pair with `release`), False = shed (caller
    answers 503 immediately), else a Future the caller awaits via
    `wait_queued`. Single-event-loop use only (no locking — ServingCore
    dispatch is the sole caller).

    Tenant QoS (ISSUE 12): within each priority class the queue is a
    set of per-tenant subqueues drained by deficit round robin — each
    rotation visit tops a tenant's deficit up by its weight
    (util/tenancy, default 1.0) and a grant costs 1, so over any
    backlogged window each tenant's admitted share tracks its weight
    share regardless of arrival order; an idle tenant's deficit resets
    (no banking), and a cancelled queued waiter is skipped without
    touching ANY tenant's deficit. Per-tenant token-bucket rate/byte
    quotas shed with reason="quota" before any queueing — the same
    pre-rendered µs 503 + Retry-After as every other shed."""

    def __init__(
        self,
        server: str,
        limiter: Optional[AdaptiveLimiter] = None,
        read_budget_s: Optional[float] = None,
        max_queue: int = 512,
        retry_after_s: Optional[float] = None,
        clock=time.monotonic,
    ):
        self.server = server
        # per-process unique identity: server NAMES repeat in in-process
        # clusters (three volume servers are all "volume") — the metric
        # series and the shell's cluster-wide merge must tell the gates
        # apart or distinct gates silently collapse into one
        self.gate_id = str(next(_GATE_SEQ))
        self.limiter = limiter or AdaptiveLimiter()
        if read_budget_s is None:
            read_budget_s = _env_f("SEAWEEDFS_TPU_ADMIT_BUDGET_MS", 50.0) / 1e3
        self.set_read_budget(read_budget_s)
        self.max_queue = max_queue
        self.retry_after_s = (
            retry_after_s
            if retry_after_s is not None
            else _env_f("SEAWEEDFS_TPU_RETRY_AFTER_S", 1.0)
        )
        self._clock = clock
        self.inflight = 0
        self.admitted_total = 0
        self.queued = 0
        # DRR state, per class: tenant -> subqueue of waiter futures,
        # the tenant rotation (a tenant is in the rotation iff its
        # subqueue is non-empty), and per-tenant deficits
        self._tq: tuple = tuple({} for _ in range(N_CLASSES))
        self._rrq: tuple = tuple(deque() for _ in range(N_CLASSES))
        self._deficit: tuple = tuple({} for _ in range(N_CLASSES))
        # live queued waiter -> (tenant name, quota-charged body bytes)
        self._fut_tenant: dict = {}
        self._tenants: dict = {}  # name -> _TenantState
        self._label_queued: dict = {}  # label -> aggregate queued
        self.shed_total = 0
        # per-label metric-child caches, all invalidated together when
        # the label policy purges a retirement (generation check): a
        # stale cached child would re-mint the purged series on its
        # next inc, and the caches would grow with CUMULATIVE label
        # churn instead of staying bounded by the live top-K
        self._children_gen = tenancy.purge_generation()
        self._shed_children: dict = {}
        self._tadm_children: dict = {}  # label -> TENANT_ADMITTED child
        self._tlat_children: dict = {}  # label -> latency hist child
        self.last_shed_t = 0.0
        self._depth_gauge = ADMISSION_QUEUE_DEPTH
        self._limit_gauge = ADMISSION_LIMIT
        self._limit_gauge.set(
            self.limiter.limit, server=server, gate=self.gate_id
        )
        # server-side latency of ADMITTED requests (admission wait +
        # service), log-bucketed — the number "admitted-request p99"
        # honestly means: a saturated open-loop *generator's* own client
        # backlog cannot pollute it
        self.admitted_counts = [0] * _LAT_BUCKETS

    def set_read_budget(self, read_budget_s: float) -> None:
        """Reset the per-class queue-wait budgets from the foreground-read
        budget (benches scale it from a measured baseline p99)."""
        self.queue_budget_s = tuple(
            read_budget_s * s for s in _BUDGET_SCALE
        )

    # -- tenants --
    def _tenant(self, name: str) -> _TenantState:
        ts = self._tenants.get(name)
        if ts is None:
            ts = self._tenants[name] = _TenantState(
                name,
                tenancy.CONFIG.weight(name),
                tenancy.CONFIG.quota_for(name, clock=self._clock),
            )
            # stamp recency BEFORE the prune can run: a fresh state at
            # t_seen=0.0 would sort first among the victims and the
            # insertion that triggered the prune would evict ITSELF —
            # the in-flight request would then book against an orphan
            # (and a set_tenant_quota call would silently lose its
            # quota before it could pin the state)
            ts.t_seen = self._clock()
            if len(self._tenants) > max(128, 8 * tenancy.POLICY.cap):
                self._prune_tenants(keep=ts)
        return ts

    def _prune_tenants(self, keep=None) -> None:
        """Bound the per-gate tenant table: principal names are
        client-controlled pre-auth (the header, a sprayed access key),
        so without eviction a million one-shot names is a memory DoS
        one layer below the bounded label policy. Evict the
        longest-idle states that hold NO live obligations — nothing
        queued (and nothing published into a depth gauge), nothing
        in flight (a released request must find its state to return
        the inflight count and charge response bytes), not pinned by
        an operator's set_tenant_* call, and — for quota'd states —
        idle past the bucket's refill horizon, so eviction grants
        nothing that natural refill would not have (a tenant cannot
        spray names to evict its own byte DEBT). A clean config-
        derived quota state is evictable: re-derived fresh on next
        sight, which a name-cycling client gets anyway under per-name
        quotas."""
        cap = max(128, 8 * tenancy.POLICY.cap)
        now = self._clock()
        victims = sorted(
            (
                ts
                for ts in self._tenants.values()
                if ts is not keep  # never the state being inserted:
                # when victims are scarce (everything else pinned or
                # busy) recency alone cannot protect it
                and not ts.pinned
                and ts.queued == 0
                and ts.pub_queued == 0
                and ts.inflight == 0
                and ts.name != _DEFAULT_TENANT
                and (
                    ts.quota is None
                    or now - ts.t_seen >= ts.quota.refill_horizon_s()
                )
            ),
            key=lambda ts: ts.t_seen,
        )
        drop = len(self._tenants) - cap // 2
        for ts in victims[:drop]:
            del self._tenants[ts.name]

    def set_tenant_quota(
        self, name: str, qps: float = 0.0, byte_ps: float = 0.0,
        burst_s: float = 1.0,
    ) -> None:
        """Install/replace one tenant's quota buckets (bench legs and
        shell tooling; env config covers the deployed path)."""
        ts = self._tenant(name)
        ts.pinned = True  # operator-installed: survives table pruning
        ts.quota = (
            tenancy.TenantQuota(
                qps=qps, byte_ps=byte_ps, burst_s=burst_s,
                clock=self._clock,
            )
            if (qps > 0.0 or byte_ps > 0.0)
            else None
        )

    def set_tenant_weight(self, name: str, weight: float) -> None:
        ts = self._tenant(name)
        ts.pinned = True  # operator-installed: survives table pruning
        ts.weight = min(100.0, max(0.1, weight))

    def tenant_admitted_counts(self, name: str) -> list:
        """Snapshot of one tenant's log-bucketed server-side admitted
        latency counts (see latency_percentile); zeros when unseen."""
        ts = self._tenants.get(name)
        return (
            list(ts.admitted_counts)
            if ts is not None
            else [0] * _LAT_BUCKETS
        )

    def _tenant_depth(self, ts: _TenantState) -> None:
        """Publish ts's queued count into the per-LABEL depth gauge.
        Labels collapse many tenants (everyone past top-K is 'other'),
        so the gauge must be the SUM over tenants sharing the label —
        a per-tenant set() would under-report and a drained tenant
        would zero out another's real backlog. Incremental O(1): each
        state remembers what it last published where."""
        label = tenancy.tenant_label(ts.name)
        lq = self._label_queued
        old = ts.pub_label
        if old is None or old == label:
            lq[label] = lq.get(label, 0) + ts.queued - ts.pub_queued
        else:
            # the tenant's label migrated (top-K retirement/admission):
            # move its published share between the aggregates. The OLD
            # label's series must never be re-MINTED here — after a
            # retirement the purge removed it, and a .set() (even to 0)
            # would re-insert it and grow cumulative cardinality with
            # every ever-retired name. Drained -> remove the series;
            # still-shared but retired -> leave it absent (internal
            # bookkeeping continues; the last publisher removes it).
            left = lq.get(old, 0) - ts.pub_queued
            if left > 0:
                lq[old] = left
                if (
                    old == tenancy.OTHER_LABEL
                    or tenancy.POLICY.peek_label(old) == old
                ):
                    TENANT_QUEUE_DEPTH.set(
                        left, server=self.server, gate=self.gate_id,
                        tenant=old,
                    )
            else:
                lq.pop(old, None)
                TENANT_QUEUE_DEPTH.remove(
                    server=self.server, gate=self.gate_id, tenant=old
                )
            lq[label] = lq.get(label, 0) + ts.queued
        ts.pub_label = label
        ts.pub_queued = ts.queued
        TENANT_QUEUE_DEPTH.set(
            lq[label], server=self.server, gate=self.gate_id,
            tenant=label,
        )

    def _check_children_gen(self) -> None:
        gen = tenancy.purge_generation()
        if gen != self._children_gen:
            self._children_gen = gen
            self._shed_children.clear()
            self._tadm_children.clear()
            self._tlat_children.clear()

    def _count_admitted(self, name: str) -> None:
        self._check_children_gen()
        label = tenancy.tenant_label(name)
        child = self._tadm_children.get(label)
        if child is None:
            child = self._tadm_children[label] = TENANT_ADMITTED.child(
                server=self.server, tenant=label
            )
        child.inc()

    # -- admission --
    def try_admit(
        self,
        cls: int,
        waited_s: float = 0.0,
        tenant: Optional[str] = None,
        cost_bytes: int = 0,
    ):
        name = tenant or _DEFAULT_TENANT
        ts = self._tenants.get(name)
        if ts is None:
            ts = self._tenant(name)
        _POLICY_NOTE(name)  # heat feeds the top-K label policy
        ts.t_seen = self._clock()  # recency for the table prune
        if waited_s > self.queue_budget_s[cls]:
            self._shed(cls, "deadline", name)
            return False
        if self.inflight < self.limiter.limit:
            # quota is consulted LAST, only for a request the gate
            # would otherwise take: charging a token and then shedding
            # for deadline/queue_full would bill a compliant tenant
            # twice for one overload
            if ts.quota is not None and not ts.quota.try_take(
                cost_bytes
            ):
                self._shed(cls, "quota", name)
                return False
            self.inflight += 1
            self.admitted_total += 1
            ts.admitted += 1
            ts.inflight += 1
            self._count_admitted(name)
            return True
        if self.queued >= self.max_queue * _QUEUE_SHARE[cls]:
            self._shed(cls, "queue_full", name)
            return False
        if ts.quota is not None and not ts.quota.try_take(cost_bytes):
            self._shed(cls, "quota", name)
            return False
        fut = asyncio.get_event_loop().create_future()
        tq = self._tq[cls]
        q = tq.get(name)
        if q is None:
            q = tq[name] = deque()
        if not q:
            # invariant: a tenant is in the class rotation iff its
            # subqueue is non-empty (husks included — they drain lazily)
            self._rrq[cls].append(name)
        q.append(fut)
        self._fut_tenant[fut] = (name, cost_bytes)
        self.queued += 1
        ts.queued += 1
        self._depth_gauge.set(
            self.queued, server=self.server, gate=self.gate_id
        )
        self._tenant_depth(ts)
        return fut

    def _drop_queued(self, fut) -> None:
        """A queued waiter stopped waiting (timeout/cancel): stop
        counting it NOW; the husk itself drains lazily in _next_queued
        without touching any tenant's deficit. The quota tokens charged
        at enqueue are REFUNDED — the request was never served, and a
        kept token would shed the tenant's next compliant request with
        reason=quota on top of the deadline shed it already paid."""
        info = self._fut_tenant.pop(fut, None)
        self.queued -= 1
        self._depth_gauge.set(
            self.queued, server=self.server, gate=self.gate_id
        )
        if info is not None:
            name, cost_bytes = info
            ts = self._tenants.get(name)
            if ts is not None:
                ts.queued -= 1
                if ts.quota is not None:
                    ts.quota.refund(cost_bytes)
                self._tenant_depth(ts)

    async def wait_queued(self, cls: int, fut, waited_s: float = 0.0) -> bool:
        """Await a queued admission inside the class's remaining budget;
        past it the request sheds (reason=deadline)."""
        left = max(self.queue_budget_s[cls] - waited_s, 0.001)
        try:
            await asyncio.wait_for(fut, left)
        except asyncio.TimeoutError:
            # wait_for cancelled the future; _next_queued skips
            # cancelled entries lazily — only the live count must drop
            # NOW
            info = self._fut_tenant.get(fut)
            self._drop_queued(fut)
            self._shed(cls, "deadline", info[0] if info else None)
            return False
        except asyncio.CancelledError:
            # the caller's task died while queued (client disconnect mid
            # overload — the exact regime this gate exists for). Undo the
            # bookkeeping or the gate leaks: if _wake granted the slot in
            # the race window before our cancellation landed, hand the
            # inflight slot back (release() will never run for us);
            # otherwise the future is a husk — stop counting it toward
            # the queue depth, same as the timeout path.
            if fut.done() and not fut.cancelled():
                # granted in the race window: hand back the gate slot
                # AND the per-tenant bookkeeping — a leaked ts.inflight
                # would pin the state unevictable forever (the prune
                # requires inflight == 0), and the quota token bought
                # no service
                self.inflight -= 1
                info = self._fut_tenant.pop(fut, None)
                if info is not None:
                    ts = self._tenants.get(info[0])
                    if ts is not None:
                        if ts.inflight > 0:
                            ts.inflight -= 1
                        if ts.quota is not None:
                            ts.quota.refund(info[1])
                self._wake()
            else:
                fut.cancel()
                self._drop_queued(fut)
            raise
        self._fut_tenant.pop(fut, None)
        return True

    async def admit(self, cls: int, waited_s: float = 0.0,
                    tenant: Optional[str] = None) -> bool:
        r = self.try_admit(cls, waited_s, tenant)
        if r is True or r is False:
            return r
        return await self.wait_queued(cls, r, waited_s)

    def release(
        self,
        latency_s: Optional[float] = None,
        total_s: Optional[float] = None,
        tenant: Optional[str] = None,
        resp_bytes: int = 0,
    ) -> None:
        """`latency_s` is the handler service wall (feeds the AIMD
        limiter), `total_s` the full server-side latency since parse
        completion (wait + service — feeds the admitted histograms);
        `tenant`/`resp_bytes` charge the response against the tenant's
        byte quota and its per-tenant latency series."""
        self.inflight -= 1
        if latency_s is not None:
            lim = self.limiter
            before = lim.limit
            lim.on_sample(latency_s, self.inflight + 1)
            if lim.limit != before:
                self._limit_gauge.set(
                    lim.limit, server=self.server, gate=self.gate_id
                )
        if total_s is not None:
            if total_s < _LAT_BASE:
                i = 0
            else:
                i = min(
                    int(math.log(total_s / _LAT_BASE) / _LAT_LOG_G),
                    _LAT_BUCKETS - 1,
                )
            self.admitted_counts[i] += 1
        # unattributed requests were ADMITTED under the default tenant
        # (try_admit's `tenant or _DEFAULT_TENANT`): release must book
        # them the same way, or a wildcard byte quota never sees the
        # default pool's response bytes and its latency series is
        # asymmetric with its admitted counter
        name = tenant or _DEFAULT_TENANT
        ts = self._tenants.get(name)
        if ts is not None:
            if ts.inflight > 0:
                ts.inflight -= 1
            if ts.quota is not None and resp_bytes:
                ts.quota.charge_bytes(resp_bytes)
        if total_s is not None:
            if ts is not None:
                ts.admitted_counts[i] += 1
            self._check_children_gen()
            label = tenancy.tenant_label(name)
            child = self._tlat_children.get(label)
            if child is None:
                child = self._tlat_children[label] = (
                    TENANT_ADMITTED_SECONDS.child(
                        server=self.server, tenant=label
                    )
                )
            child.observe(total_s)
        self._wake()

    def _next_queued(self):
        """The next waiter to grant: classes in priority order, tenants
        within a class by deficit round robin. Returns (fut, cls, name)
        or None. Cancelled husks are dropped WITHOUT touching deficits:
        tenant A's cancelled waiters can neither spend A's deficit nor
        leak B's (the PR 9 regression class, per-tenant edition)."""
        for cls in range(N_CLASSES):
            rr = self._rrq[cls]
            if not rr:
                continue
            tq = self._tq[cls]
            dq = self._deficit[cls]
            # bounded: each full rotation tops every tenant up by >= 0.1
            # (the clamped min weight), so <= 10 rotations reach a
            # deficit of 1; the +len guard absorbs husk-only drains
            guard = 12 * len(rr) + 16
            while rr and guard > 0:
                guard -= 1
                name = rr[0]
                q = tq.get(name)
                while q and q[0].done():
                    # husk (cancelled waiter): already uncounted by
                    # _drop_queued; deficits untouched
                    q.popleft()
                if not q:
                    # subqueue drained: out of the rotation, deficit
                    # resets — an idle tenant cannot bank credit
                    tq.pop(name, None)
                    dq.pop(name, None)
                    rr.popleft()
                    continue
                d = dq.get(name, 0.0)
                if d >= 1.0:
                    fut = q.popleft()
                    if q:
                        dq[name] = d - 1.0
                    else:
                        del tq[name]
                        dq.pop(name, None)
                        rr.popleft()
                    return fut, cls, name
                ts = self._tenants.get(name)
                dq[name] = d + (ts.weight if ts is not None else 1.0)
                rr.rotate(-1)
            if guard <= 0 and rr:
                # defensive: force progress rather than spin (cannot
                # happen with weights clamped >= 0.1, kept for safety)
                name = rr[0]
                q = tq.get(name)
                if q:
                    return q.popleft(), cls, name
        return None

    def _wake(self) -> None:
        """Hand freed slots to queued waiters: highest class first,
        weighted-fair across tenants within the class."""
        while self.inflight < self.limiter.limit and self.queued:
            nxt = self._next_queued()
            if nxt is None:
                return  # only cancelled husks remained
            fut, _cls, name = nxt
            # the map entry survives the grant: wait_queued pops it on
            # resume — the granted-then-cancelled race needs it to
            # return the tenant's inflight count and refund the quota
            self.queued -= 1
            self._depth_gauge.set(
                self.queued, server=self.server, gate=self.gate_id
            )
            ts = self._tenants.get(name)
            if ts is not None:
                ts.queued -= 1
                ts.admitted += 1
                ts.inflight += 1
                self._tenant_depth(ts)
            self.inflight += 1
            self.admitted_total += 1
            self._count_admitted(name)
            fut.set_result(True)

    # -- out-of-band byte attribution (ISSUE 13 satellites) --
    def charge_member_bytes(
        self,
        tenant: Optional[str],
        nbytes: int,
        carrier: Optional[str] = None,
    ) -> bool:
        """Re-attribute one member's share of an admitted MIXED-tenant
        batch frame (the filer's host-coalesced `!batch/put`) from the
        carrier principal to the member's own: consult + charge the
        member's byte bucket, then hand the same bytes back to the
        carrier's bucket (which paid for the whole frame body at
        admission) — each needle's bytes end up billed to exactly the
        principal that wrote it. False = the member is over its byte
        quota; the item declines item-wise (reason=quota counted here)
        and the client retries it through the single-needle path under
        the member's own principal, where the full admission path is
        authoritative."""
        name = tenant or _DEFAULT_TENANT
        ts = self._tenant(name)
        _POLICY_NOTE(name)
        ts.t_seen = self._clock()
        # the member pays its FULL quota — request token + bytes — the
        # same bill its needle would have paid as an unbatched volume
        # HTTP request (each chunk was one request before coalescing),
        # so host-coalesced batching cannot become a qps-quota bypass
        ok = ts.quota is None or ts.quota.try_take(nbytes)
        # the carrier is refunded EITHER way: on success the bytes now
        # bill the member; on decline the item is never written and a
        # kept charge would let one over-quota member's sustained
        # traffic drain the default pool's bucket and shed unrelated
        # anonymous writes (cross-tenant leakage through the carrier)
        cname = carrier or _DEFAULT_TENANT
        if cname != name:
            cts = self._tenants.get(cname)
            if cts is not None and cts.quota is not None:
                cts.quota.refund_bytes(nbytes)
        if not ok:
            self._shed(CLASS_WRITE, "quota", name)
            return False
        return True

    def charge_rpc_bytes(self, tenant: Optional[str], nbytes: int) -> bool:
        """gRPC request-message bytes against the tenant's byte quota —
        the pb/rpc.py handler seam (quotas were HTTP-only before; the
        gRPC plane moved volume copies and bulk reads for free). False
        = over quota: the handler refuses with RESOURCE_EXHAUSTED and
        the shed is counted class="rpc", reason="quota".

        UNTENANTED calls (no x-seaweed-tenant metadata) are exempt on
        purpose: the gRPC plane's anonymous traffic is the cluster's
        own control plane — master repair/vacuum/lifecycle dispatches,
        heartbeat side-calls — and a wildcard byte quota drained by
        tenant HTTP traffic must never shed cluster MAINTENANCE (the
        coupling would let foreground load starve repairs). A tenant
        principal only rides the metadata when a real request context
        flows through the hop, which is exactly the traffic the quota
        is for."""
        if tenant is None:
            return True
        ts = self._tenant(tenant)
        _POLICY_NOTE(tenant)
        ts.t_seen = self._clock()
        if ts.quota is not None and not ts.quota.try_take_bytes(nbytes):
            self._shed("rpc", "quota", tenant)
            return False
        return True

    def charge_rpc_response(
        self, tenant: Optional[str], nbytes: int
    ) -> None:
        """Response-message bytes at RPC completion (may drive the
        bucket negative, exactly like the HTTP release path). Exempt
        for untenanted control-plane calls like charge_rpc_bytes."""
        if tenant is None:
            return
        ts = self._tenants.get(tenant)
        if ts is not None and ts.quota is not None and nbytes:
            ts.quota.charge_bytes(nbytes)

    # -- shedding / pressure --
    def _shed(
        self, cls, reason: str, tenant: Optional[str] = None
    ) -> None:
        # cls: priority-class index, or a literal class label for
        # traffic outside the HTTP class lattice (e.g. "rpc")
        name = tenant or _DEFAULT_TENANT
        self.shed_total += 1
        self.last_shed_t = self._clock()
        ts = self._tenants.get(name)
        if ts is not None:
            ts.shed += 1
        self._check_children_gen()
        label = tenancy.tenant_label(name)
        key = (cls, reason, label)
        child = self._shed_children.get(key)
        if child is None:
            child = self._shed_children[key] = OVERLOAD_SHED.child(
                server=self.server,
                gate=self.gate_id,
                reason=reason,
                tenant=label,
                **{
                    "class": (
                        CLASS_NAMES[cls] if isinstance(cls, int) else cls
                    )
                },
            )
        child.inc()

    def pressure(self) -> float:
        """Foreground pressure in [0, 1]: 1.0 while shedding (within the
        last second), else queue fullness."""
        if self._clock() - self.last_shed_t < 1.0:
            return 1.0
        if self.queued:
            return min(1.0, self.queued / self.max_queue)
        return 0.0

    def stats(self) -> dict:
        lim = self.limiter
        return {
            "server": self.server,
            "gate": self.gate_id,
            "limit": lim.limit,
            "baseline_ms": (
                round(lim.baseline_s * 1e3, 3)
                if lim.baseline_s is not None
                else None
            ),
            "limit_decreases": lim.decreases,
            "limit_increases": lim.increases,
            "inflight": self.inflight,
            "queued": self.queued,
            "admitted_total": self.admitted_total,
            "shed_total": self.shed_total,
            "queue_budget_ms": [
                round(b * 1e3, 1) for b in self.queue_budget_s
            ],
            "admitted_p50_ms": round(
                latency_percentile(self.admitted_counts, 50) * 1e3, 3
            ),
            "admitted_p99_ms": round(
                latency_percentile(self.admitted_counts, 99) * 1e3, 3
            ),
            "pressure": round(self.pressure(), 3),
            "tenants": self.tenant_stats(),
        }

    def tenant_stats(self, limit: int = 24) -> dict:
        """Per-tenant view (top `limit` by admitted+shed — the stats
        payload must stay bounded on a million-principal box): weight,
        admitted/shed/queued counts, and quota bucket fill."""
        names = sorted(
            self._tenants,
            key=lambda n: -(
                self._tenants[n].admitted + self._tenants[n].shed
            ),
        )[:limit]
        out = {}
        for n in names:
            ts = self._tenants[n]
            row = {
                "weight": ts.weight,
                "admitted": ts.admitted,
                "shed": ts.shed,
                "queued": ts.queued,
                "label": tenancy.POLICY.peek_label(n),
            }
            if ts.quota is not None:
                row["quota"] = ts.quota.snapshot()
            out[n] = row
        return out


# ------------------------------------------------- gate registry/pressure --

_GATES: list = []
_GATE_SEQ = count(1)  # per-process unique gate ids (names repeat)


def admission_enabled() -> bool:
    return (os.environ.get("SEAWEEDFS_TPU_ADMIT", "1") or "1") not in (
        "0",
        "",
    )


def new_server_gate(server: str) -> Optional[AdmissionGate]:
    """An AdmissionGate for one ServingCore, registered into the global
    pressure signal — or None when admission is disabled by env."""
    if not admission_enabled():
        return None
    gate = AdmissionGate(server)
    _GATES.append(gate)
    return gate


def drop_gate(gate: Optional[AdmissionGate]) -> None:
    """Unregister a stopped server's gate so its last-shed window cannot
    keep pressuring maintenance after the server is gone."""
    if gate is not None:
        try:
            _GATES.remove(gate)
        except ValueError:
            pass


def global_pressure() -> float:
    """Max pressure over every live gate in this process — the signal
    storage/maintenance.py consults. Plain float reads: safe from worker
    threads."""
    p = 0.0
    for g in _GATES:
        gp = g.pressure()
        if gp > p:
            p = gp
            if p >= 1.0:
                break
    return p


def gate_stats() -> list:
    return [g.stats() for g in _GATES]


# ------------------------------------------------------- circuit breaker --

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitOpenError(ConnectionError):
    """Fast-fail for calls to a peer whose breaker is open. A
    ConnectionError on purpose: every existing retry/hedge/failover path
    already treats it as 'peer unavailable' and moves on."""


class CircuitBreaker:
    """Per-peer closed/open/half-open breaker.

    Opens on `fail_threshold` consecutive failures, or when at least
    half of the last `shed_window` outcomes were sheds (503 +
    Retry-After: the peer is alive but actively load-shedding — keep
    hammering it and you ARE the overload). Half-open admits one probe
    after the open window; the probe's outcome closes or re-opens. The
    probe slot leases for `probe_timeout_s`: a probe whose caller never
    reports (cancelled mid-flight, caller died) is reclaimed after the
    lease instead of wedging allow() shut until process restart."""

    def __init__(
        self,
        peer: str,
        fail_threshold: int = 6,
        shed_window: int = 20,
        shed_trip: float = 0.5,
        open_s: float = 0.25,
        probe_timeout_s: float = 5.0,
        clock=time.monotonic,
    ):
        self.peer = peer
        self.fail_threshold = fail_threshold
        self.shed_trip = shed_trip
        self.open_s = open_s
        self.probe_timeout_s = probe_timeout_s
        self._clock = clock
        self.state = CLOSED
        self.opens = 0  # times tripped
        self._consec_fail = 0
        self._ring: deque = deque(maxlen=shed_window)  # True = shed
        self._open_until = 0.0
        self._probe_out = False
        self._probe_deadline = 0.0
        self._last_shed_t = 0.0

    # -- gate --
    def allow(self) -> bool:
        """May a request go to this peer now? Consumes the half-open
        probe slot, so callers must report the outcome via record_*
        (record_cancelled when the request is abandoned outcome-less)."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() < self._open_until:
                return False
            self._transition(HALF_OPEN)
            return self._lease_probe()
        if self._probe_out and self._clock() < self._probe_deadline:
            return False  # half-open: one probe at a time
        # no probe out — or the in-flight probe outlived its lease
        # without reporting: reclaim the slot rather than refuse forever
        return self._lease_probe()

    def _lease_probe(self) -> bool:
        self._probe_out = True
        self._probe_deadline = self._clock() + self.probe_timeout_s
        return True

    def blocked(self) -> bool:
        """Non-consuming peek: would allow() refuse right now? (Replica
        ordering uses this so peeking never eats the half-open probe.)"""
        if self.state == CLOSED:
            return False
        if self.state == OPEN:
            return self._clock() < self._open_until
        return self._probe_out and self._clock() < self._probe_deadline

    def shedding(self) -> bool:
        """Is the peer actively load-shedding? True within ~1s of a shed
        answer — the read fan-out pauses hedging into such a pool (a
        hedge into a shedding peer is pure retry-storm fuel)."""
        return self._clock() - self._last_shed_t < 1.0

    # -- outcomes --
    def record_success(self) -> None:
        self._consec_fail = 0
        self._ring.append(False)
        self._probe_out = False
        if self.state != CLOSED:
            self._transition(CLOSED)

    def record_failure(self) -> None:
        self._consec_fail += 1
        self._ring.append(False)
        if self.state == HALF_OPEN:
            self._probe_out = False
            self._trip(self.open_s)  # failed probe: back to open
        elif self.state == CLOSED and (
            self._consec_fail >= self.fail_threshold
        ):
            self._trip(self.open_s)

    def record_cancelled(self) -> None:
        """The caller abandoned its request before an outcome was known
        (hedged reads losing their race are cancelled routinely). Says
        nothing about the peer's health — but if the request held the
        half-open probe slot it MUST be returned here, or allow()
        refuses the peer until the probe lease expires."""
        if self.state == HALF_OPEN:
            self._probe_out = False

    def record_shed(self, retry_after_s: Optional[float] = None) -> None:
        """A 503/429 shed answer (alive peer refusing load). Not a
        failure for the consecutive count — but a shed-heavy window
        trips the breaker for the peer's own Retry-After hint."""
        self._ring.append(True)
        self._last_shed_t = self._clock()
        if self.state == HALF_OPEN:
            self._probe_out = False
            self._trip(retry_after_s or self.open_s)
            return
        ring = self._ring
        if (
            self.state == CLOSED
            and len(ring) >= ring.maxlen // 2
            and sum(ring) >= len(ring) * self.shed_trip
        ):
            self._trip(retry_after_s or self.open_s)

    def _trip(self, open_for: float) -> None:
        self._transition(OPEN)
        self._open_until = self._clock() + open_for
        self.opens += 1
        self._consec_fail = 0
        self._ring.clear()

    def _transition(self, to: str) -> None:
        if to == self.state:
            return
        self.state = to
        CIRCUIT_TRANSITIONS.inc(peer=self.peer, to=to)
        CIRCUIT_OPEN.set(1.0 if to == OPEN else 0.0, peer=self.peer)


class BreakerRegistry:
    """Process-wide per-peer breakers, shared by the HTTP data-plane
    client and the gRPC stub so both views of one peer's health feed one
    breaker."""

    def __init__(self, **breaker_kwargs):
        self._kw = breaker_kwargs
        self._by_peer: dict[str, CircuitBreaker] = {}

    def get(self, peer: str) -> CircuitBreaker:
        br = self._by_peer.get(peer)
        if br is None:
            br = self._by_peer[peer] = CircuitBreaker(peer, **self._kw)
        return br

    def peek(self, peer: str) -> Optional[CircuitBreaker]:
        return self._by_peer.get(peer)

    def reset(self) -> None:
        self._by_peer.clear()

    def stats(self) -> dict:
        return {
            p: {"state": b.state, "opens": b.opens}
            for p, b in self._by_peer.items()
        }


BREAKERS = BreakerRegistry()


def breakers_enabled() -> bool:
    return (os.environ.get("SEAWEEDFS_TPU_BREAKER", "1") or "1") not in (
        "0",
        "",
    )


def peer_breaker(peer: str) -> Optional[CircuitBreaker]:
    """The shared breaker for a peer address, or None when breakers are
    disabled (env) — callers do `br is None or br.allow()`."""
    if not breakers_enabled():
        return None
    return BREAKERS.get(peer)
