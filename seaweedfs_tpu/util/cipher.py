"""Client-side chunk content encryption.

Mirrors the reference's util/cipher.go: AES-256-GCM with a fresh random
32-byte key per chunk and the 12-byte nonce prefixed to the ciphertext
(ref: weed/util/cipher.go:15-60; used by the upload path
weed/operation/upload_content.go:30,66-95 with the key carried in the
chunk metadata, and decrypted on the filer/mount read path). The volume
server only ever sees ciphertext; possession of the filer metadata is
what grants plaintext access.
"""

from __future__ import annotations

import os

_NONCE_SIZE = 12  # GCM standard nonce


def _aesgcm(key: bytes):
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError as e:  # pragma: no cover - baked into this image
        raise RuntimeError(
            "content cipher requires the 'cryptography' package"
        ) from e
    return AESGCM(key)


def gen_cipher_key() -> bytes:
    """Fresh random 256-bit chunk key (ref GenCipherKey)."""
    return os.urandom(32)


def encrypt(plaintext: bytes, key: bytes) -> bytes:
    """nonce || AES-256-GCM(ciphertext+tag) (ref Encrypt)."""
    nonce = os.urandom(_NONCE_SIZE)
    return nonce + _aesgcm(key).encrypt(nonce, bytes(plaintext), None)


def decrypt(ciphertext: bytes, key: bytes) -> bytes:
    """Inverse of encrypt (ref Decrypt); raises ValueError on a short
    buffer or authentication failure."""
    if len(ciphertext) < _NONCE_SIZE:
        raise ValueError("ciphertext too short")
    nonce, body = ciphertext[:_NONCE_SIZE], ciphertext[_NONCE_SIZE:]
    try:
        return _aesgcm(key).decrypt(nonce, bytes(body), None)
    except Exception as e:
        raise ValueError(f"chunk decrypt failed: {e}") from e
