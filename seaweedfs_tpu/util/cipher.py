"""Client-side chunk content encryption.

Mirrors the reference's util/cipher.go: AES-256-GCM with a fresh random
32-byte key per chunk and the 12-byte nonce prefixed to the ciphertext
(ref: weed/util/cipher.go:15-60; used by the upload path
weed/operation/upload_content.go:30,66-95 with the key carried in the
chunk metadata, and decrypted on the filer/mount read path). The volume
server only ever sees ciphertext; possession of the filer metadata is
what grants plaintext access.

Uses the `cryptography` package when available; otherwise falls back to a
pure-Python AES-256-GCM (FIPS-197 + NIST SP 800-38D). The fallback is
correct but slow (~100 KB/s) — fine for the KB-sized chunk payloads this
code path actually carries, and it keeps the cipher feature working on
images without the native wheel.
"""

from __future__ import annotations

import os

_NONCE_SIZE = 12  # GCM standard nonce


def _aesgcm(key: bytes):
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM

        return AESGCM(key)
    except ImportError:
        return _PurePythonAESGCM(key)


def gen_cipher_key() -> bytes:
    """Fresh random 256-bit chunk key (ref GenCipherKey)."""
    return os.urandom(32)


def encrypt(plaintext: bytes, key: bytes) -> bytes:
    """nonce || AES-256-GCM(ciphertext+tag) (ref Encrypt)."""
    nonce = os.urandom(_NONCE_SIZE)
    return nonce + _aesgcm(key).encrypt(nonce, bytes(plaintext), None)


def decrypt(ciphertext: bytes, key: bytes) -> bytes:
    """Inverse of encrypt (ref Decrypt); raises ValueError on a short
    buffer or authentication failure."""
    if len(ciphertext) < _NONCE_SIZE:
        raise ValueError("ciphertext too short")
    nonce, body = ciphertext[:_NONCE_SIZE], ciphertext[_NONCE_SIZE:]
    try:
        return _aesgcm(key).decrypt(nonce, bytes(body), None)
    except Exception as e:
        raise ValueError(f"chunk decrypt failed: {e}") from e


# ------------------------------------------------- pure-Python fallback --
# AES-256 per FIPS-197 with the S-box derived from the GF(2^8) inverse +
# affine map (no hand-typed table to mistype), GCM per SP 800-38D with
# GHASH done on 128-bit Python ints. Tables built lazily on first use.

_SBOX: list | None = None
_TAG_SIZE = 16


def _build_sbox() -> list:
    # GF(2^8) exp/log over generator 3, then inverse + affine transform
    exp = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        x ^= (x << 1) ^ (0x11B if x & 0x80 else 0)
        x &= 0xFF
    log = [0] * 256
    for i in range(255):
        log[exp[i]] = i
    sbox = [0] * 256
    for v in range(256):
        inv = 0 if v == 0 else exp[(255 - log[v]) % 255]
        b = inv
        res = 0x63
        for _ in range(4):
            b = ((b << 1) | (b >> 7)) & 0xFF
            res ^= b
        sbox[v] = res ^ inv
    return sbox


def _sbox() -> list:
    global _SBOX
    if _SBOX is None:
        _SBOX = _build_sbox()
    return _SBOX


def _expand_key_256(key: bytes) -> list:
    """AES-256 key schedule -> 15 round keys of 16 bytes each."""
    sbox = _sbox()
    words = [list(key[i : i + 4]) for i in range(0, 32, 4)]
    rcon = 1
    for i in range(8, 60):
        t = list(words[i - 1])
        if i % 8 == 0:
            t = t[1:] + t[:1]
            t = [sbox[b] for b in t]
            t[0] ^= rcon
            rcon = (rcon << 1) ^ (0x11B if rcon & 0x80 else 0)
            rcon &= 0xFF
        elif i % 8 == 4:
            t = [sbox[b] for b in t]
        words.append([a ^ b for a, b in zip(words[i - 8], t)])
    return [
        bytes(b for w in words[r * 4 : r * 4 + 4] for b in w)
        for r in range(15)
    ]


# ShiftRows as a flat index permutation over the column-major state
_SHIFT = [0, 5, 10, 15, 4, 9, 14, 3, 8, 13, 2, 7, 12, 1, 6, 11]


def _encrypt_block(round_keys: list, block: bytes) -> bytes:
    sbox = _sbox()
    s = [b ^ k for b, k in zip(block, round_keys[0])]
    for rnd in range(1, 15):
        s = [sbox[s[i]] for i in _SHIFT]
        if rnd < 14:
            t = []
            for c in range(0, 16, 4):
                a0, a1, a2, a3 = s[c : c + 4]
                x = a0 ^ a1 ^ a2 ^ a3
                t.append(a0 ^ x ^ _xt(a0 ^ a1))
                t.append(a1 ^ x ^ _xt(a1 ^ a2))
                t.append(a2 ^ x ^ _xt(a2 ^ a3))
                t.append(a3 ^ x ^ _xt(a3 ^ a0))
            s = t
        s = [b ^ k for b, k in zip(s, round_keys[rnd])]
    return bytes(s)


def _xt(b: int) -> int:
    b <<= 1
    return (b ^ 0x1B) & 0xFF if b & 0x100 else b


_R = 0xE1 << 120  # GHASH reduction poly x^128 + x^7 + x^2 + x + 1


def _ghash_mult(x: int, y: int) -> int:
    """Carryless multiply in GF(2^128), MSB-first bit order per SP 800-38D."""
    z = 0
    v = y
    for i in range(127, -1, -1):
        if (x >> i) & 1:
            z ^= v
        v = (v >> 1) ^ _R if v & 1 else v >> 1
    return z


class _PurePythonAESGCM:
    """Drop-in for cryptography's AESGCM (encrypt/decrypt with nonce and
    optional AAD), AES-256 only — the only key size this repo generates."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("pure-python fallback supports AES-256 only")
        self._rk = _expand_key_256(bytes(key))
        self._h = int.from_bytes(_encrypt_block(self._rk, b"\x00" * 16), "big")

    def _ctr_stream(self, j0: bytes, n_bytes: int) -> bytes:
        out = bytearray()
        prefix, ctr = j0[:12], int.from_bytes(j0[12:], "big")
        for _ in range((n_bytes + 15) // 16):
            ctr = (ctr + 1) & 0xFFFFFFFF
            out += _encrypt_block(self._rk, prefix + ctr.to_bytes(4, "big"))
        return bytes(out[:n_bytes])

    def _ghash(self, aad: bytes, ct: bytes) -> int:
        y = 0
        for blob in (aad, ct):
            for i in range(0, len(blob), 16):
                block = blob[i : i + 16].ljust(16, b"\x00")
                y = _ghash_mult(y ^ int.from_bytes(block, "big"), self._h)
        lens = (len(aad) * 8).to_bytes(8, "big") + (len(ct) * 8).to_bytes(
            8, "big"
        )
        return _ghash_mult(y ^ int.from_bytes(lens, "big"), self._h)

    def _j0(self, nonce: bytes) -> bytes:
        if len(nonce) == 12:
            return nonce + b"\x00\x00\x00\x01"
        # SP 800-38D: J0 = GHASH(nonce padded to a block boundary) folded
        # with ONE final block of 0^64 || [len(nonce) in bits]_64
        padded = nonce + b"\x00" * ((16 - len(nonce) % 16) % 16)
        y = 0
        for i in range(0, len(padded), 16):
            y = _ghash_mult(
                y ^ int.from_bytes(padded[i : i + 16], "big"), self._h
            )
        y = _ghash_mult(y ^ (len(nonce) * 8), self._h)
        return y.to_bytes(16, "big")

    def encrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        aad = aad or b""
        j0 = self._j0(nonce)
        ct = bytes(
            a ^ b for a, b in zip(data, self._ctr_stream(j0, len(data)))
        )
        s = self._ghash(aad, ct)
        tag = int.from_bytes(_encrypt_block(self._rk, j0), "big") ^ s
        return ct + tag.to_bytes(16, "big")

    def decrypt(self, nonce: bytes, data: bytes, aad) -> bytes:
        aad = aad or b""
        if len(data) < _TAG_SIZE:
            raise ValueError("ciphertext shorter than the GCM tag")
        ct, tag = data[:-_TAG_SIZE], data[-_TAG_SIZE:]
        j0 = self._j0(nonce)
        s = self._ghash(aad, ct)
        want = int.from_bytes(_encrypt_block(self._rk, j0), "big") ^ s
        # constant-time-ish compare (int xor) — this is a test-image
        # fallback, but there is no reason to be sloppy about it
        if want ^ int.from_bytes(tag, "big"):
            raise ValueError("GCM tag mismatch")
        return bytes(
            a ^ b for a, b in zip(ct, self._ctr_stream(j0, len(ct)))
        )
