import os as _os


def available_cpus() -> int:
    """CPUs this process may actually run on (cgroup/affinity-aware;
    sched_getaffinity is Linux-only, cpu_count the portable fallback)."""
    try:
        return len(_os.sched_getaffinity(0))
    except AttributeError:
        return _os.cpu_count() or 1
