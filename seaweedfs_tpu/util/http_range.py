"""RFC 9110 single-range parsing shared by the volume server and the S3
gateway (ref: Go net/http ServeContent range handling used at
weed/server/volume_server_handlers_read.go writeResponseContent)."""

from __future__ import annotations

from typing import Optional, Tuple, Union

RangeResult = Union[Tuple[int, int], str, None]


def parse_range(rng: str, total: int) -> RangeResult:
    """-> (start, end) inclusive | None (serve full body) |
    "invalid-range" (416 unsatisfiable).

    Unparsable or syntactically invalid specs (including end < start,
    RFC 9110 §14.1.1) are ignored -> None; only a well-formed range whose
    start is past EOF yields 416.
    """
    if not rng.startswith("bytes=") or "," in rng:
        return None
    start_s, sep, end_s = rng[len("bytes="):].strip().partition("-")
    if not sep:
        return None
    try:
        if start_s == "":
            if end_s == "":
                return None
            if int(end_s) == 0:
                # 'bytes=-0' is a zero-length suffix: unsatisfiable per
                # RFC 9110 (matches Go http.ServeContent)
                return "invalid-range"
            start, end = max(0, total - int(end_s)), total - 1
        else:
            start = int(start_s)
            end = int(end_s) if end_s else total - 1
    except ValueError:
        return None
    if start < 0 or end < start:
        return None
    if start >= total:
        return "invalid-range"
    return min(start, total - 1), min(end, total - 1)
