"""Capped, jittered exponential backoff with deadline propagation, plus
the shared RetryBudget that keeps retries from amplifying an overload.

Shared by every serving-path retry loop (master-client lookups, EC remote
shard reads, keep-connected reconnects, filer chunk-delete GC) so they
all have the same shape: full-jitter delays (AWS architecture blog's
`random(0, min(cap, base*2^k))` — the variant that best de-correlates a
thundering herd), a hard attempt cap, and an absolute deadline that both
truncates sleeps and refuses to start attempts it cannot finish. Pass a
seeded `random.Random` for deterministic tests.

The **RetryBudget** (the gRPC retry-throttling shape) is a token bucket
refilled by *successes*: each success deposits `ratio` (default 0.1)
tokens, each retryable failure withdraws one, and retries are permitted
only while the bucket holds more than half its capacity. Under a healthy
peer the bucket stays full and every retry goes through; under a failing
or overloaded peer the bucket drains in ~`max_tokens` failures and
retries are *suppressed* (`retries_suppressed_total{op}`) until real
successes refill it — so the aggregate retry rate is capped at ~`ratio`
of successful traffic and a brownout cannot snowball into a retry storm.
One process-global budget (`shared_retry_budget()`) is consulted by
`retry_async` and by the read fan-out's hedges; loops that must retry
forever (keep-connected) fall back to their capped delay when the budget
says no, instead of giving up.
"""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from .metrics import RETRIES_SUPPRESSED, RETRY_COUNTER


@dataclass(frozen=True)
class BackoffPolicy:
    base: float = 0.05  # first-retry delay upper bound (seconds)
    cap: float = 2.0  # per-delay ceiling
    multiplier: float = 2.0
    attempts: int = 4  # total tries, including the first

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter delay before retry number `attempt` (0-based)."""
        return rng.uniform(0.0, min(self.cap, self.base * self.multiplier**attempt))


DEFAULT_POLICY = BackoffPolicy()


class RetryBudget:
    """Token-bucket retry throttle (the gRPC retryThrottling shape).

    Starts full; `on_success()` deposits `ratio` tokens (capped),
    `on_failure()` withdraws 1, and `allow(op)` permits a retry only
    while the bucket holds more than half its capacity — counting every
    refusal into `retries_suppressed_total{op}`. Thread-safe: consulted
    from the event loop and from maintenance threads alike."""

    def __init__(self, ratio: float = 0.1, max_tokens: float = 100.0):
        self.ratio = ratio
        self.max_tokens = max_tokens
        self.tokens = max_tokens
        self._lock = threading.Lock()

    def on_success(self) -> None:
        with self._lock:
            self.tokens = min(self.max_tokens, self.tokens + self.ratio)

    def on_failure(self) -> None:
        with self._lock:
            self.tokens = max(0.0, self.tokens - 1.0)

    def allow(self, op: str = "") -> bool:
        with self._lock:
            ok = self.tokens > self.max_tokens / 2.0
        if not ok:
            RETRIES_SUPPRESSED.inc(op=op or "unknown")
        return ok

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tokens": round(self.tokens, 2),
                "max_tokens": self.max_tokens,
                "ratio": self.ratio,
            }


_SHARED_BUDGET: Optional[RetryBudget] = None
_SHARED_BUDGET_LOCK = threading.Lock()


def shared_retry_budget() -> Optional[RetryBudget]:
    """The process-wide retry budget every retry loop consults. Tunable
    via SEAWEEDFS_TPU_RETRY_BUDGET_RATIO (default 0.1 — retries capped
    at ~10% of successes) and SEAWEEDFS_TPU_RETRY_BUDGET_TOKENS (bucket
    size, default 100; 0 disables the budget entirely)."""
    global _SHARED_BUDGET
    if _SHARED_BUDGET is not None:
        return _SHARED_BUDGET
    try:
        tokens = float(
            os.environ.get("SEAWEEDFS_TPU_RETRY_BUDGET_TOKENS", "") or 100.0
        )
        ratio = float(
            os.environ.get("SEAWEEDFS_TPU_RETRY_BUDGET_RATIO", "") or 0.1
        )
    except ValueError:
        tokens, ratio = 100.0, 0.1
    if tokens <= 0:
        return None
    with _SHARED_BUDGET_LOCK:
        if _SHARED_BUDGET is None:
            _SHARED_BUDGET = RetryBudget(ratio=ratio, max_tokens=tokens)
        return _SHARED_BUDGET


def configure_retry_budget(budget: Optional[RetryBudget]) -> None:
    """Install (or clear, to re-read env) the process budget — tests."""
    global _SHARED_BUDGET
    with _SHARED_BUDGET_LOCK:
        _SHARED_BUDGET = budget


def deadline_after(seconds: Optional[float]) -> Optional[float]:
    """Relative budget -> absolute time.monotonic() deadline (None passes
    through: no deadline)."""
    return None if seconds is None else time.monotonic() + seconds


def remaining(deadline: Optional[float], default: Optional[float] = None,
              floor: float = 0.001) -> Optional[float]:
    """Seconds left until an absolute deadline, for per-call timeouts.
    None deadline -> `default`. Never returns less than `floor`, so a
    just-expired deadline yields a timeout that fails fast rather than a
    negative value some APIs treat as infinite."""
    if deadline is None:
        return default
    return max(floor, deadline - time.monotonic())


_SHARED = object()  # sentinel: "use the process-wide retry budget"


async def retry_async(
    fn: Callable[[], Awaitable],
    *,
    policy: BackoffPolicy = DEFAULT_POLICY,
    deadline: Optional[float] = None,
    retry_on: tuple = (Exception,),
    rng: Optional[random.Random] = None,
    op: str = "",
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    budget=_SHARED,
    delay_floor: Optional[Callable[[], float]] = None,
) -> object:
    """Run `fn()` (a zero-arg coroutine factory) with backoff.

    `deadline` is absolute (time.monotonic()); sleeps are truncated to it
    and no retry starts past it — the budget propagates into `fn` via
    `remaining(deadline)` at the call site. The last exception is re-raised
    when attempts or deadline run out. Retries count into
    seaweedfs_tpu_retries_total{op=...}.

    `budget` is the shared RetryBudget by default: retryable failures
    withdraw, and a drained budget SUPPRESSES further retries (the last
    exception surfaces immediately) so a sick peer costs each caller one
    attempt, not a storm. Successes deposit ONLY for an explicitly
    passed budget — the shared one is already fed by the transports
    (FastHTTPClient.request / GrpcStub.call deposit every completed
    response), and depositing here too would double the effective
    retry-to-success ratio. Pass budget=None to opt a loop out. `delay_floor` (e.g. a peer's Retry-After hint via
    FastHTTPClient.retry_after_remaining) raises individual sleeps to at
    least its value — the peer asked for breathing room, jitter must not
    undercut it; the deadline still wins (a retry past it is refused
    either way).
    """
    rng = rng or random
    deposit = budget is not _SHARED
    if budget is _SHARED:
        budget = shared_retry_budget()
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        try:
            result = await fn()
        except retry_on as e:
            last = e
            if budget is not None:
                budget.on_failure()
        else:
            if budget is not None and deposit:
                budget.on_success()
            return result
        if attempt == policy.attempts - 1:
            break
        if budget is not None and not budget.allow(op):
            break
        d = policy.delay(attempt, rng)
        if delay_floor is not None:
            d = max(d, delay_floor())
        if deadline is not None:
            left = deadline - time.monotonic()
            if left <= 0:
                break
            d = min(d, left)
        if op:
            RETRY_COUNTER.inc(op=op)
        if on_retry is not None:
            on_retry(attempt, last)
        await asyncio.sleep(d)
        if deadline is not None and time.monotonic() >= deadline:
            break
    assert last is not None
    raise last
