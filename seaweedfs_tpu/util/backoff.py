"""Capped, jittered exponential backoff with deadline propagation.

Shared by every serving-path retry loop (master-client lookups, EC remote
shard reads, keep-connected reconnects) so they all have the same shape:
full-jitter delays (AWS architecture blog's `random(0, min(cap, base*2^k))`
— the variant that best de-correlates a thundering herd), a hard attempt
cap, and an absolute deadline that both truncates sleeps and refuses to
start attempts it cannot finish. Pass a seeded `random.Random` for
deterministic tests.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from .metrics import RETRY_COUNTER


@dataclass(frozen=True)
class BackoffPolicy:
    base: float = 0.05  # first-retry delay upper bound (seconds)
    cap: float = 2.0  # per-delay ceiling
    multiplier: float = 2.0
    attempts: int = 4  # total tries, including the first

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter delay before retry number `attempt` (0-based)."""
        return rng.uniform(0.0, min(self.cap, self.base * self.multiplier**attempt))


DEFAULT_POLICY = BackoffPolicy()


def deadline_after(seconds: Optional[float]) -> Optional[float]:
    """Relative budget -> absolute time.monotonic() deadline (None passes
    through: no deadline)."""
    return None if seconds is None else time.monotonic() + seconds


def remaining(deadline: Optional[float], default: Optional[float] = None,
              floor: float = 0.001) -> Optional[float]:
    """Seconds left until an absolute deadline, for per-call timeouts.
    None deadline -> `default`. Never returns less than `floor`, so a
    just-expired deadline yields a timeout that fails fast rather than a
    negative value some APIs treat as infinite."""
    if deadline is None:
        return default
    return max(floor, deadline - time.monotonic())


async def retry_async(
    fn: Callable[[], Awaitable],
    *,
    policy: BackoffPolicy = DEFAULT_POLICY,
    deadline: Optional[float] = None,
    retry_on: tuple = (Exception,),
    rng: Optional[random.Random] = None,
    op: str = "",
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
) -> object:
    """Run `fn()` (a zero-arg coroutine factory) with backoff.

    `deadline` is absolute (time.monotonic()); sleeps are truncated to it
    and no retry starts past it — the budget propagates into `fn` via
    `remaining(deadline)` at the call site. The last exception is re-raised
    when attempts or deadline run out. Retries count into
    seaweedfs_tpu_retries_total{op=...}.
    """
    rng = rng or random
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        try:
            return await fn()
        except retry_on as e:
            last = e
        if attempt == policy.attempts - 1:
            break
        d = policy.delay(attempt, rng)
        if deadline is not None:
            left = deadline - time.monotonic()
            if left <= 0:
                break
            d = min(d, left)
        if op:
            RETRY_COUNTER.inc(op=op)
        if on_retry is not None:
            on_retry(attempt, last)
        await asyncio.sleep(d)
        if deadline is not None and time.monotonic() >= deadline:
            break
    assert last is not None
    raise last
