"""Masked CRC32-Castagnoli needle checksum.

The reference computes CRC32C over the needle data and stores a *masked* value:
``value = rotr15(crc) + 0xa282ead8 (mod 2^32)``
(ref: weed/storage/needle/crc.go:12-25 — klauspost/crc32 Castagnoli table,
Value() = (c>>15 | c<<17) + 0xa282ead8).

Uses the C-accelerated google-crc32c when present, with a pure-Python
table fallback so the package has no hard native dependency.
"""

from __future__ import annotations

try:
    import google_crc32c as _gcrc

    def crc32c(data, init: int = 0) -> int:
        if type(data) is not bytes:
            # google-crc32c's C binding accepts only bytes and objects
            # exposing __array_interface__; the serving data plane hands
            # zero-copy memoryviews through here, and wrapping them in a
            # numpy view keeps the CRC zero-copy too
            try:
                import numpy as _np

                data = _np.frombuffer(data, _np.uint8)
            except Exception:
                data = bytes(data)
        return _gcrc.extend(init, data)

except ImportError:  # pragma: no cover - fallback path
    _POLY = 0x82F63B78  # reversed Castagnoli
    _TABLE = []
    for _i in range(256):
        _c = _i
        for _ in range(8):
            _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
        _TABLE.append(_c)

    def crc32c(data: bytes, init: int = 0) -> int:
        c = init ^ 0xFFFFFFFF
        for b in data:
            c = _TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
        return c ^ 0xFFFFFFFF


class CRC:
    """Incremental CRC mirroring the reference's needle.CRC type."""

    __slots__ = ("raw",)

    def __init__(self, raw: int = 0):
        self.raw = raw & 0xFFFFFFFF

    def update(self, data: bytes) -> "CRC":
        return CRC(crc32c(data, self.raw))

    def value(self) -> int:
        """Masked checksum as stored on disk (ref: crc.go:23-25)."""
        c = self.raw
        return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def new_crc(data: bytes) -> CRC:
    return CRC(0).update(data)


def masked_crc(data: bytes) -> int:
    return new_crc(data).value()
