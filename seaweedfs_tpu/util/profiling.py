"""CPU/memory profiling hooks (ref: weed/command/volume.go:55-81 -cpuprofile/
-memprofile/-pprof, weed/command/benchmark.go:119-126, util/grace/pprof.go).

Python equivalents of the Go pprof flags: cProfile stats files for the CPU
profile, tracemalloc snapshots for the memory profile, and on-demand HTTP
handlers (/debug/pprof/...) for a live server.
"""

from __future__ import annotations

import cProfile
import io
import pstats
from typing import Optional


class Profiler:
    """Process-wide profile collection started by CLI flags and dumped on
    shutdown (the Go flags' start-at-boot, write-at-exit semantics)."""

    def __init__(self, cpu_path: str = "", mem_path: str = ""):
        self.cpu_path = cpu_path
        self.mem_path = mem_path
        self._cpu: Optional[cProfile.Profile] = None

    def start(self) -> "Profiler":
        if self.cpu_path:
            self._cpu = cProfile.Profile()
            self._cpu.enable()
        if self.mem_path:
            import tracemalloc

            tracemalloc.start(10)
        return self

    def __enter__(self) -> "Profiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop_and_dump()

    def stop_and_dump(self) -> None:
        if self._cpu is not None:
            self._cpu.disable()
            self._cpu.dump_stats(self.cpu_path)  # load with pstats.Stats
            self._cpu = None
        if self.mem_path:
            import tracemalloc

            snapshot = tracemalloc.take_snapshot()
            with open(self.mem_path, "w") as f:
                for stat in snapshot.statistics("lineno")[:200]:
                    f.write(f"{stat}\n")
            tracemalloc.stop()


def profile_sorted_text(profile: cProfile.Profile, limit: int = 50) -> str:
    """Human-readable cumulative-time report for HTTP handlers."""
    buf = io.StringIO()
    stats = pstats.Stats(profile, stream=buf)
    stats.sort_stats(pstats.SortKey.CUMULATIVE)
    stats.print_stats(limit)
    return buf.getvalue()


_profile_lock = None  # created lazily on the serving event loop


async def handle_pprof_profile(request):
    """GET /debug/pprof/profile?seconds=N — profile the event loop's
    process for N seconds and return the report (ref util/grace/pprof.go).

    cProfile is process-global, so requests serialize on a lock and the
    profiler always disables (even on client disconnect); a boot-level
    -cpuprofile already holds the C profiler, which surfaces as a 409.
    """
    import asyncio

    from aiohttp import web

    global _profile_lock
    if _profile_lock is None:
        _profile_lock = asyncio.Lock()

    try:
        seconds = min(float(request.query.get("seconds", 5)), 120.0)
    except ValueError:
        return web.Response(status=400, text="bad seconds parameter\n")
    async with _profile_lock:
        prof = cProfile.Profile()
        try:
            prof.enable()
        except ValueError as e:  # another profiler (e.g. -cpuprofile) active
            return web.Response(status=409, text=f"{e}\n")
        try:
            await asyncio.sleep(seconds)
        finally:
            prof.disable()
    return web.Response(text=profile_sorted_text(prof), content_type="text/plain")


async def handle_pprof_heap(request):
    """GET /debug/pprof/heap — tracemalloc top allocations (starts
    tracemalloc on first use)."""
    import tracemalloc

    from aiohttp import web

    if not tracemalloc.is_tracing():
        tracemalloc.start(10)
        return web.Response(
            text="tracemalloc started; call again for a snapshot\n",
            content_type="text/plain",
        )
    snapshot = tracemalloc.take_snapshot()
    lines = [str(s) for s in snapshot.statistics("lineno")[:100]]
    return web.Response(text="\n".join(lines) + "\n", content_type="text/plain")


# --- on-demand start/stop/dump profiling of a LIVE server (ISSUE 8
# satellite: the docstring's promised /debug/pprof handlers, wired onto
# ServingCore's shared cold-tier middleware for every server type).
# Unlike /debug/pprof/profile (fixed window), start/stop bracket an
# operator-chosen workload; dump renders the captured stats — while the
# profiler is still running it snapshots (disable -> render -> enable).

_live_profiler: Optional[cProfile.Profile] = None
_live_running = False


async def handle_pprof_start(request):
    """GET /debug/pprof/start — begin collecting; 409 when a collection
    is already active (cProfile is process-global)."""
    from aiohttp import web

    global _live_profiler, _live_running
    if _live_running:
        return web.Response(status=409, text="profile already running\n")
    prof = cProfile.Profile()
    try:
        prof.enable()
    except ValueError as e:  # another profiler (-cpuprofile) holds the hook
        return web.Response(status=409, text=f"{e}\n")
    _live_profiler, _live_running = prof, True
    return web.Response(text="profiling started\n", content_type="text/plain")


async def handle_pprof_stop(request):
    """GET /debug/pprof/stop — stop collecting; the stats stay in memory
    for /debug/pprof/dump."""
    from aiohttp import web

    global _live_running
    if not _live_running or _live_profiler is None:
        return web.Response(status=409, text="no profile running\n")
    _live_profiler.disable()
    _live_running = False
    return web.Response(text="profiling stopped\n", content_type="text/plain")


async def handle_pprof_dump(request):
    """GET /debug/pprof/dump[?limit=N] — cumulative-time report of the
    last start/stop collection (snapshots a still-running one)."""
    from aiohttp import web

    if _live_profiler is None:
        return web.Response(status=404, text="no profile collected\n")
    try:
        limit = min(int(request.query.get("limit", 50)), 500)
    except ValueError:
        return web.Response(status=400, text="bad limit parameter\n")
    if _live_running:
        _live_profiler.disable()
        try:
            text = profile_sorted_text(_live_profiler, limit)
        finally:
            _live_profiler.enable()
    else:
        text = profile_sorted_text(_live_profiler, limit)
    return web.Response(text=text, content_type="text/plain")
