"""One default aiohttp client timeout for every session in the tree.

ISSUE 9's timeout discipline (enforced by the tier-1 static scan in
tests/test_timeout_discipline.py): every outbound request path carries a
deadline — an unbounded wait against a hung peer is how one sick server
wedges its callers' queues and turns a brownout into an outage. The
byte-level `FastHTTPClient` and the gRPC `Stub.call` carry their own
per-request defaults (30s); aiohttp sessions get this shared
`ClientTimeout` at construction:

- `sock_connect=10`: a peer that cannot even complete a TCP handshake
  in 10s is down — fail to the retry/breaker machinery, don't camp;
- `sock_read=60`: every individual read must make progress within 60s.
  Deliberately a PER-READ bound with no `total`: the sessions carrying
  large transfers (replication sinks, mount chunk reads, backup
  downloads) must not abort a healthy multi-minute body, while a peer
  that stops sending mid-body still fails in bounded time. Long-lived
  subscription streams ride gRPC `server_stream` (the allowlisted
  streaming API), never these sessions.
"""

from __future__ import annotations


def client_timeout(
    total: float | None = None,
    sock_connect: float = 10.0,
    sock_read: float = 60.0,
):
    """The default `aiohttp.ClientTimeout` (lazy import: aiohttp is a
    cold-path dependency for several callers)."""
    import aiohttp

    return aiohttp.ClientTimeout(
        total=total, sock_connect=sock_connect, sock_read=sock_read
    )
