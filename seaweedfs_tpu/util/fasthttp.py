"""Minimal HTTP/1.1 data-plane machinery: a raw asyncio.Protocol server and
a keep-alive client pool.

Why this exists: the serving north star (BASELINE.json config 4 — the
reference's `weed benchmark`, README.md:483-530) is bounded by per-request
framework overhead, not by storage. The reference's data plane is Go
net/http (weed/server/volume_server_handlers_read.go); the Python-general
equivalent (aiohttp) spends ~200µs/request on routing, header objects,
multidicts and response assembly — an order of magnitude more than the
needle read itself. This module is the TPU-framework analogue of the
reference's thin handler loop: a byte-level parser feeding registered fast
handlers, with EVERY other request transparently proxied to the full
aiohttp application (which keeps the long-tail surface: UIs, pprof, tiered
reads, ranges, resizing...). One listening port, two tiers.

Design rules:
- hot handlers may return FALLBACK at any point; the raw request bytes are
  then replayed verbatim against the internal aiohttp listener, so the two
  tiers can never disagree about semantics — the fast tier only ever serves
  requests it fully understands.
- parsing is bytes-only and allocation-light: no multidicts, no URL
  objects, headers lazily split into a plain dict of lower-cased names.
- responses are assembled as one writev-style bytes join with pre-rendered
  static fragments.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from typing import Awaitable, Callable, Optional

from . import faults, overload, tenancy, trace
from .backoff import shared_retry_budget

_perf = time.perf_counter  # bound once: stamped per parsed request
_cur_tenant = tenancy.current  # bound once: read per client request

FALLBACK = object()  # sentinel: "proxy this request to the full app"
DETACHED = object()  # sentinel: "the handler will write the response itself
# (via req.transport) from a later callback" — used by batch continuations
# so N coalesced responses cost one callback, not N task resumes

_MAX_HEADER = 64 * 1024
_MAX_BODY = 256 << 20  # matches the aiohttp client_max_size

_STATUS_LINES = {
    200: b"HTTP/1.1 200 OK\r\n",
    201: b"HTTP/1.1 201 Created\r\n",
    202: b"HTTP/1.1 202 Accepted\r\n",
    204: b"HTTP/1.1 204 No Content\r\n",
    206: b"HTTP/1.1 206 Partial Content\r\n",
    304: b"HTTP/1.1 304 Not Modified\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    401: b"HTTP/1.1 401 Unauthorized\r\n",
    403: b"HTTP/1.1 403 Forbidden\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    405: b"HTTP/1.1 405 Method Not Allowed\r\n",
    416: b"HTTP/1.1 416 Range Not Satisfiable\r\n",
    429: b"HTTP/1.1 429 Too Many Requests\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    503: b"HTTP/1.1 503 Service Unavailable\r\n",
}


class FastRequest:
    """One parsed request. Header names are lower-case byte strings.
    `t_arrive` is the perf_counter at parse completion: the admission
    gate charges event-loop backlog (time between parse and dispatch)
    against the request's queue budget — a request that already waited
    past its class deadline is shed before doing work."""

    __slots__ = ("method", "target", "path", "query", "headers", "body", "peer",
                 "raw_head", "transport", "done", "t_arrive")

    def __init__(self, method, target, headers, body, peer, raw_head):
        self.method = method  # str: "GET"
        self.target = target  # str: "/3,0144b9f3d1?x=1" (raw)
        self.headers = headers  # dict[bytes, bytes] lower-cased names
        self.body = body  # bytes
        self.peer = peer  # str remote ip
        self.raw_head = raw_head  # bytes: request line + headers + CRLFCRLF
        q = target.find("?")
        if q < 0:
            self.path = target
            self.query = ""
        else:
            self.path = target[:q]
            self.query = target[q + 1:]


def finish_detached(req: FastRequest, response: bytes) -> None:
    """Write a DETACHED request's response and release its connection's
    request loop (see FastHTTPProtocol._run). Idempotent: a second call
    for the same request is a no-op, never a second response on the
    wire."""
    d = req.done
    if d is True or (d is not None and d is not True and d.done()):
        return
    t = req.transport
    if t is not None and not t.is_closing():
        t.write(response)
    if d is None:
        req.done = True
    else:
        d.set_result(None)


def render_response(
    status: int,
    body: bytes = b"",
    content_type: bytes = b"application/json",
    extra: bytes = b"",
    keep_alive: bool = True,
    head_only: bool = False,
) -> bytes:
    """One response byte string. `extra` is pre-rendered \r\n-terminated
    header lines."""
    return b"".join(
        (
            _STATUS_LINES.get(status) or (
                b"HTTP/1.1 %d X\r\n" % status
            ),
            b"Content-Type: ", content_type, b"\r\n",
            b"Content-Length: %d\r\n" % len(body),
            extra,
            b"Connection: keep-alive\r\n\r\n"
            if keep_alive
            else b"Connection: close\r\n\r\n",
            b"" if head_only else body,
        )
    )


Handler = Callable[[FastRequest], Awaitable[object]]


class _ReqQueue:
    """Single-producer single-consumer request queue: a deque plus one
    waiter future. asyncio.Queue's per-op loop bookkeeping (getter/putter
    deques, loop resolution, wakeup scheduling) was measurable per request
    at serving QPS rates; the protocol's strictly 1:1 shape needs none of
    it."""

    __slots__ = ("_d", "_waiter")

    def __init__(self):
        self._d: deque = deque()
        self._waiter: Optional[asyncio.Future] = None

    def put_nowait(self, item) -> None:
        self._d.append(item)
        w = self._waiter
        if w is not None and not w.done():
            w.set_result(None)

    def empty(self) -> bool:
        return not self._d

    async def get(self):
        while not self._d:
            self._waiter = asyncio.get_event_loop().create_future()
            try:
                await self._waiter
            finally:
                self._waiter = None
        return self._d.popleft()


class FastHTTPProtocol(asyncio.Protocol):
    """HTTP/1.1 server protocol: sequential requests per connection,
    Content-Length bodies (chunked uploads fall back), keep-alive."""

    def __init__(self, server: "FastHTTPServer"):
        self.server = server
        self.transport: Optional[asyncio.Transport] = None
        self.buf = bytearray()
        self.peer = ""
        self._task: Optional[asyncio.Task] = None
        self._queue: _ReqQueue = _ReqQueue()
        self._paused = False
        self._closed = False
        self._continued = False  # 100 Continue sent for the pending request
        self._processing = False  # a request's response is still pending
        self._want_continue = False  # 100 deferred until the conn is idle
        # kernel-buffer flow control (pause_writing/resume_writing): relays
        # await _drain_waiter instead of polling get_write_buffer_size()
        self._write_paused = False
        self._drain_waiter: Optional[asyncio.Future] = None
        # backpressure threshold for the CURRENT partial request: raised by
        # _try_parse once the request's frame size is known, so a request
        # whose total frame slightly exceeds _MAX_BODY (body under the cap,
        # headers on top — ADVICE r4) completes instead of deadlocking in
        # pause_reading with no resume
        self._pause_limit = _MAX_BODY
        # in-progress chunked-body decode state (pos/out/head/...): decoding
        # resumes where it left off so each data_received touches only NEW
        # bytes — a restart-from-scratch walk re-copies every prior chunk
        # and goes quadratic in body size
        self._chunked: Optional[dict] = None

    # -- transport events --
    def connection_made(self, transport):
        self.transport = transport
        transport.set_write_buffer_limits(high=1 << 20)
        peer = transport.get_extra_info("peername")
        self.peer = peer[0] if peer else ""
        self._task = asyncio.ensure_future(self._run())
        self.server._conns.add(self)

    def connection_lost(self, exc):
        self._closed = True
        self._queue.put_nowait(None)
        self.server._conns.discard(self)
        w = self._drain_waiter
        if w is not None and not w.done():
            w.set_result(None)  # waiters wake and see is_closing()
        self._drain_waiter = None
        if self._task is not None:
            self._task.cancel()

    # -- outgoing flow control (transport write-buffer watermarks) --
    def pause_writing(self):
        self._write_paused = True

    def resume_writing(self):
        self._write_paused = False
        w = self._drain_waiter
        if w is not None and not w.done():
            w.set_result(None)
        self._drain_waiter = None

    async def drain(self):
        """Wait until the transport's write buffer falls under the low
        watermark (or the connection dies — callers re-check is_closing).
        The event-driven replacement for sleep-polling
        get_write_buffer_size() in paced relays."""
        if not self._write_paused or self._closed:
            return
        w = self._drain_waiter
        if w is None or w.done():
            w = asyncio.get_event_loop().create_future()
            self._drain_waiter = w
        await w

    def data_received(self, data: bytes):
        self.buf += data
        self._pump()
        # backpressure: stop reading while too much is queued (never on a
        # transport _fail() just closed — pause_reading would raise and
        # asyncio's fatal-error path discards the buffered 400)
        if (
            len(self.buf) > self._pause_limit
            and not self._paused
            and not self._closed
        ):
            self._paused = True
            self.transport.pause_reading()

    def _pump(self):
        """Slice complete requests out of the buffer into the queue."""
        while True:
            req = self._try_parse()
            if req is None:
                return
            self._queue.put_nowait(req)

    def _try_parse(self):
        if self._chunked is not None:
            return self._resume_chunked()
        buf = self.buf
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            if len(buf) > _MAX_HEADER:
                self._fail(400)
            return None
        head = bytes(buf[: end + 4])
        try:
            line_end = head.index(b"\r\n")
            method, _, rest = head[:line_end].partition(b" ")
            target, _, _version = rest.rpartition(b" ")
            headers: dict = {}
            pos = line_end + 2
            while pos < end:
                nl = head.index(b"\r\n", pos)
                colon = head.index(b":", pos, nl)
                name = head[pos:colon].lower()
                headers[name] = head[colon + 1: nl].strip()
                pos = nl + 2
        except ValueError:
            self._fail(400)
            return None
        te = headers.get(b"transfer-encoding")
        if te is not None:
            # de-chunk Transfer-Encoding bodies (VERDICT r4 missing #1):
            # the reference's Go net/http accepts streaming uploads
            # transparently, so clients sending unknown-length bodies
            # (curl -T from a pipe, SDK streaming modes) must work here
            # too. The assembled body is handed to handlers with a
            # synthesized Content-Length head so FALLBACK replay frames
            # identically on the backend leg.
            if te.lower() != b"chunked":
                self._fail(400)  # gzip/deflate transfer codings: not spoken
                return None
            self._chunked = {
                "pos": 0,
                "out": bytearray(),
                "head": head,
                "method": method,
                "target": target,
                "headers": headers,
                "in_trailer": False,
            }
            del buf[:end + 4]  # head is captured; buf holds framing only
            return self._resume_chunked()
        try:
            clen = int(headers.get(b"content-length", b"0") or 0)
        except ValueError:
            # non-numeric Content-Length must 400, not raise out of
            # data_received and wedge the connection (ADVICE r4)
            self._fail(400)
            return None
        if clen < 0 or clen > _MAX_BODY:
            self._fail(400)
            return None
        total = end + 4 + clen
        if len(buf) < total:
            # the frame is legal but larger than what's buffered: lift the
            # backpressure threshold to the frame's own size (+ header
            # slack) so reading always continues to completion
            self._pause_limit = total + _MAX_HEADER
            if clen:
                self._maybe_send_continue(headers)
            return None
        body = bytes(buf[end + 4: total])
        del buf[:total]
        return self._finish_request(method, target, headers, body, head)

    def _finish_request(self, method, target, headers, body, head):
        """Common tail of a successful parse: reset per-request state,
        resume reading, build the FastRequest."""
        self._pause_limit = _MAX_BODY
        # next request gets its own 100 Continue
        self._continued = False
        self._want_continue = False
        if self._paused and len(self.buf) < self._pause_limit:
            self._paused = False
            self.transport.resume_reading()
        req = FastRequest(
            method.decode("latin1"),
            target.decode("latin1"),
            headers,
            body,
            self.peer,
            head,
        )
        req.transport = self.transport
        req.done = None
        req.t_arrive = _perf()
        return req

    def _resume_chunked(self):
        """Advance the in-progress chunked-body decode; None while
        incomplete. Resumes at the cached buffer position, so every body
        byte is copied exactly once no matter how many TCP segments carry
        it. On completion the request is rebuilt as if it had arrived
        Content-Length-framed: headers and raw_head drop Transfer-Encoding
        and gain the real length, so fast handlers and the FALLBACK replay
        never see chunked framing."""
        st = self._chunked
        buf = self.buf
        out = st["out"]

        def compact() -> None:
            # consumed framing bytes are dropped on every incomplete
            # return (NOT per chunk — that would re-quadratize a large
            # buffered burst), so raw buf stays ~one in-flight chunk
            # instead of shadowing the whole decoded body at 2x memory
            if st["pos"]:
                del buf[:st["pos"]]
                st["pos"] = 0

        while True:
            if st["in_trailer"]:
                # trailer section: zero or more header lines, then CRLF
                while True:
                    tnl = buf.find(b"\r\n", st["pos"])
                    if tnl < 0:
                        if len(buf) - st["pos"] > _MAX_HEADER:
                            self._fail(400)
                        else:
                            compact()
                            self._pause_limit = len(buf) + _MAX_HEADER
                        return None
                    if tnl == st["pos"]:  # blank line ends the message
                        return self._finish_chunked(tnl + 2)
                    st["pos"] = tnl + 2  # trailer line: parsed over, dropped
            nl = buf.find(b"\r\n", st["pos"])
            if nl < 0:
                # cap matches the complete-line tolerance (chunk extensions
                # are legal and can be long) so acceptance never depends on
                # TCP segmentation; Go's chunked reader allows 4096
                if len(buf) - st["pos"] > 4096:
                    self._fail(400)
                else:
                    compact()
                    self._pause_limit = len(buf) + _MAX_BODY + _MAX_HEADER
                    self._maybe_send_continue(st["headers"])
                return None
            if nl - st["pos"] > 4096:
                self._fail(400)
                return None
            token = bytes(buf[st["pos"]:nl]).split(b";")[0]
            # strict RFC 9112 HEXDIG only, no whitespace: Python's
            # int(.., 16) also accepts '0x10'/'+10'/'1_0'/' 5', and a
            # parser more liberal than the strict intermediary in front of
            # it is a smuggling seam
            if not token or any(
                c not in b"0123456789abcdefABCDEF" for c in token
            ):
                self._fail(400)
                return None
            size = int(token, 16)
            if len(out) + size > _MAX_BODY:
                self._fail(400)
                return None
            if size == 0:
                st["in_trailer"] = True
                st["pos"] = nl + 2
                continue
            cstart = nl + 2
            cend = cstart + size
            if len(buf) < cend + 2:
                # grow the backpressure window to what this chunk needs
                shift = st["pos"]
                compact()
                self._pause_limit = (cend - shift) + 2 + _MAX_HEADER
                self._maybe_send_continue(st["headers"])
                return None
            if buf[cend:cend + 2] != b"\r\n":
                self._fail(400)
                return None
            out += buf[cstart:cend]
            st["pos"] = cend + 2

    def _finish_chunked(self, total: int):
        st = self._chunked
        self._chunked = None
        body = bytes(st["out"])
        del self.buf[:total]
        headers = dict(st["headers"])
        del headers[b"transfer-encoding"]
        headers[b"content-length"] = b"%d" % len(body)
        lines = [
            ln for ln in st["head"][:-4].split(b"\r\n")
            if not ln.lower().startswith(
                (b"transfer-encoding:", b"content-length:")
            )
        ]
        lines.append(b"Content-Length: %d" % len(body))
        new_head = b"\r\n".join(lines) + b"\r\n\r\n"
        return self._finish_request(
            st["method"], st["target"], headers, body, new_head
        )

    def _maybe_send_continue(self, headers) -> None:
        """curl (and other clients) gate bodies on a 100 Continue;
        answering immediately avoids their ~1s expectation timeout. Only
        when the connection is otherwise idle — with an earlier response
        still pending, an interim 1xx now would land BEFORE that response
        and desync the client's attribution (deferred sends happen in
        _maybe_continue once the connection drains)."""
        if (
            headers.get(b"expect", b"").lower() == b"100-continue"
            and not self._continued
        ):
            if not self._processing and self._queue.empty():
                self._continued = True
                self.transport.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            else:
                self._want_continue = True

    def _fail(self, status: int):
        self._chunked = None
        if self.transport is not None:
            try:
                self.transport.write(
                    render_response(status, b'{"error":"bad request"}',
                                    keep_alive=False)
                )
            except Exception:
                pass
            self.transport.close()
        self._closed = True
        self._queue.put_nowait(None)

    # -- request loop --
    def _maybe_continue(self) -> None:
        """Fire a deferred 100 Continue now that the connection drained
        (the body the client is withholding is the only way forward)."""
        if (
            self._want_continue
            and not self._continued
            and self.transport is not None
            and not self.transport.is_closing()
        ):
            self._continued = True
            self._want_continue = False
            self.transport.write(b"HTTP/1.1 100 Continue\r\n\r\n")

    async def _run(self):
        detached_prev = None  # last DETACHED request, possibly in flight
        try:
            while True:
                self._processing = False
                if self._queue.empty() and detached_prev is None:
                    self._maybe_continue()
                req = await self._queue.get()
                if req is None or self._closed:
                    return
                self._processing = True
                if detached_prev is not None:
                    # a previous request's response is written from a later
                    # callback; never start the next one before it lands
                    # (pipelining clients would see reordered responses)
                    if detached_prev.done is not True:
                        if detached_prev.done is None:
                            detached_prev.done = (
                                asyncio.get_event_loop().create_future()
                            )
                        try:
                            await detached_prev.done
                        except Exception:
                            pass
                    detached_prev = None
                try:
                    out = await self.server.handler(req)
                except Exception:
                    out = None
                if out is DETACHED:
                    detached_prev = req
                    continue
                if out is FALLBACK:
                    ok = await self._proxy(req)
                    if not ok:
                        return
                    continue
                if out is None:
                    self.transport.write(
                        render_response(
                            500, b'{"error":"internal error"}')
                    )
                    continue
                self.transport.write(out)
                if self.transport.is_closing():
                    return
        except asyncio.CancelledError:
            pass
        except Exception:
            if self.transport is not None:
                self.transport.close()

    async def _proxy(self, req: FastRequest) -> bool:
        resp, has_len = await proxy_request(
            self.server.backend, req, transport=self.transport
        )
        if resp:
            self.transport.write(resp)
        if not has_len:
            self.transport.close()
            return False
        return True


_STREAM_THRESHOLD = 1 << 20  # buffer small responses, stream the rest


async def _relay_paced(
    transport, data: bytes, stall_timeout: float = 60.0
) -> None:
    """Write to a protocol transport without unbounded buffering: after
    each piece, wait for the transport's flow control to signal drained
    (pause_writing fired on write when the buffer crossed the high-water
    mark; resume_writing resolves the protocol's drain future). A client
    that stops reading mid-stream holds the relay in ONE suspended await
    instead of a wakeup loop; the wait is still bounded so the caller's
    except path can drop the connection."""
    if transport.is_closing():
        # a closed client must STOP the relay loop, not look "drained" —
        # otherwise the caller pulls the whole remaining backend body
        # into a dead connection
        raise ConnectionResetError("client connection closed mid-relay")
    transport.write(data)
    proto = transport.get_protocol()
    drain = getattr(proto, "drain", None)
    if drain is not None:
        try:
            await asyncio.wait_for(drain(), stall_timeout)
        except asyncio.TimeoutError:
            raise TimeoutError("client stalled during streamed relay") from None
        if transport.is_closing():
            raise ConnectionResetError("client connection closed mid-relay")
        return
    # transports whose protocol has no drain hook: legacy sleep-poll
    waited = 0.0
    while transport.get_write_buffer_size() > _STREAM_THRESHOLD:
        if transport.is_closing():
            raise ConnectionResetError("client connection closed mid-relay")
        if waited >= stall_timeout:
            raise TimeoutError("client stalled during streamed relay")
        await asyncio.sleep(0.05)
        waited += 0.05


async def proxy_request(
    backend, req: FastRequest, transport=None
) -> tuple[bytes, bool]:
    """Replay `req` verbatim against the internal full-featured listener.
    -> (response_bytes, has_content_length). Connection: close on the
    backend leg keeps framing trivial; callers keep their client-side
    connection alive only when the response is Content-Length-framed.

    With `transport` given, a response that would be large (or has no
    Content-Length at all — e.g. a multi-GB chunked-manifest stream from
    the aiohttp tier) is relayed to it in pieces instead of being
    materialized in proxy memory (ADVICE r4); the return is then
    (b"", has_len) and the bytes are already on the wire."""
    if backend is None:
        return render_response(500, b'{"error":"no fallback app"}'), True
    try:
        r, w = await asyncio.open_connection(*backend)
        # strip any connection header, pin close framing on the backend leg
        lines = req.raw_head.split(b"\r\n")
        # drop Expect too: the body is already in hand, and relaying the
        # backend's own "100 Continue" would give the client a second one
        lines = [
            ln for ln in lines[:-2]
            if not ln.lower().startswith(
                (b"connection:", b"x-forwarded-for:", b"expect:")
            )
        ]
        # the backend sees our loopback socket, not the client: carry the
        # real peer so remote-address checks (whitelist, replicate
        # membership) keep working — util.security.real_remote() trusts
        # this header only on loopback-originated requests
        lines.append(b"X-Forwarded-For: " + req.peer.encode("latin1"))
        lines.append(b"Connection: close")
        w.write(b"\r\n".join(lines) + b"\r\n\r\n" + req.body)
        await w.drain()
        # assemble the FULL response head before classifying it: a single
        # read can legally return a partial head (status line flushed
        # before the rest), and has_len decides whether the client-side
        # connection survives — misclassifying drops pipelined requests
        resp = bytearray()
        head_end = -1
        while True:
            piece = await r.read(1 << 16)
            if not piece:
                break
            resp += piece
            head_end = resp.find(b"\r\n\r\n")
            if head_end >= 0 or len(resp) > _MAX_HEADER:
                break
        if not resp:
            w.close()
            return (
                render_response(500, b'{"error":"empty fallback response"}'),
                True,
            )
        if head_end < 0:
            # never produced a legal head within _MAX_HEADER: relay the
            # WHOLE stream verbatim close-framed (dropping the unread
            # remainder would truncate undetectably)
            rest = await r.read(-1)
            w.close()
            return bytes(resp) + rest, False
        clen = None
        for ln in bytes(resp[:head_end]).lower().split(b"\r\n"):
            if ln.startswith(b"content-length:"):
                try:
                    clen = int(ln.split(b":", 1)[1])
                except ValueError:
                    pass
        has_len = clen is not None
        total = head_end + 4 + clen if has_len else None
        if total is not None and (
            total <= _STREAM_THRESHOLD or total <= len(resp)
        ):
            # small, length-framed: buffer the remainder and return whole
            while len(resp) < total:
                piece = await r.read(total - len(resp))
                if not piece:
                    break
                resp += piece
            w.close()
            if len(resp) < total:
                # backend died mid-body: the declared length can't be
                # honored, so the client connection must not be reused
                return bytes(resp), False
            return bytes(resp), has_len
        if transport is None:
            # no sink: preserve the buffered contract
            rest = await r.read(-1)
            w.close()
            return bytes(resp) + rest, has_len
        # large or unbounded: relay piecewise (ADVICE r4 — never
        # materialize a multi-GB fallback stream in proxy memory)
        sent = len(resp)
        try:
            await _relay_paced(transport, bytes(resp))
            while True:
                piece = await r.read(_STREAM_THRESHOLD)
                if not piece:
                    break
                sent += len(piece)
                await _relay_paced(transport, piece)
        except Exception:
            # bytes are already on the wire: a 500 now would corrupt the
            # stream — drop the connection so the client sees truncation
            try:
                transport.close()
            except Exception:
                pass
            w.close()
            return b"", False
        w.close()
        if total is not None and sent < total:
            # backend truncated a length-framed stream: the client must
            # not reuse a connection mid-body
            return b"", False
        return b"", has_len
    except Exception:
        return render_response(500, b'{"error":"fallback proxy failed"}'), True


def finish_detached_proxy(server: "FastHTTPServer", req: FastRequest) -> None:
    """From a DETACHED continuation that discovered it can't finish the
    request after all: replay it against the full app asynchronously."""

    async def run() -> None:
        resp, has_len = await proxy_request(
            server.backend, req, transport=req.transport
        )
        finish_detached(req, resp)
        if not has_len and req.transport is not None:
            req.transport.close()

    t = asyncio.ensure_future(run())
    server._detached_tasks.add(t)
    t.add_done_callback(server._detached_tasks.discard)


class FastHTTPServer:
    """Owns the public listening socket; `handler` is the fast tier,
    `backend` (host, port) the full aiohttp app for everything else."""

    def __init__(self, handler: Handler, backend=None):
        self.handler = handler
        self.backend = backend
        self._server: Optional[asyncio.AbstractServer] = None
        self._conns: set = set()
        self._detached_tasks: set = set()  # strong refs (loop holds weak)

    async def start(self, host: str, port: int):
        loop = asyncio.get_event_loop()
        self._server = await loop.create_server(
            lambda: FastHTTPProtocol(self), host, port, reuse_address=True
        )

    async def stop(self):
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
        for conn in list(self._conns):
            try:
                if conn.transport is not None:
                    conn.transport.close()
            except Exception:
                pass


# ---------------------------------------------------------------- client --


def parse_retry_after(raw: bytes) -> Optional[float]:
    """Seconds from a Retry-After header value: the delta-seconds form,
    or the IMF-fixdate form (RFC 9110 §10.2.3 — standards-faithful peers
    send an HTTP-date; a quota shed's backoff floor must survive either
    spelling). None when unparseable. Cold path: only consulted on
    503/429 responses."""
    try:
        return float(raw)
    except ValueError:
        pass
    try:
        from email.utils import parsedate_to_datetime

        dt = parsedate_to_datetime(raw.decode("latin1").strip())
    except (TypeError, ValueError, IndexError, UnicodeDecodeError):
        return None
    if dt is None:
        return None
    if dt.tzinfo is None:
        # obsolete asctime form carries no zone: the RFC says GMT
        from datetime import timezone

        dt = dt.replace(tzinfo=timezone.utc)
    return max(0.0, dt.timestamp() - time.time())


class _ClientConn(asyncio.Protocol):
    """Raw-protocol client connection: one buffer, inline response parse,
    exactly ONE await per request (the completion future). The
    StreamReader formulation (readuntil + readexactly = several coroutine
    suspensions per response) was ~20-40us/request of pure machinery at
    serving-benchmark QPS rates."""

    __slots__ = ("transport", "buf", "waiter", "closed", "_loop")

    def __init__(self, loop):
        self._loop = loop
        self.transport = None
        self.buf = bytearray()
        self.waiter: Optional[asyncio.Future] = None
        self.closed = False

    # -- transport events --
    def connection_made(self, transport):
        self.transport = transport

    def data_received(self, data):
        self.buf += data
        w = self.waiter
        if w is not None and not w.done():
            self._try_complete(False)

    def eof_received(self):
        self.closed = True
        w = self.waiter
        if w is not None and not w.done():
            self._try_complete(True)
        return False

    def connection_lost(self, exc):
        self.closed = True
        w = self.waiter
        if w is not None and not w.done():
            if not self._try_complete(True):
                w.set_exception(
                    exc or ConnectionResetError("connection lost")
                )

    # -- request lifecycle --
    def begin(self) -> asyncio.Future:
        self.waiter = self._loop.create_future()
        return self.waiter

    def _try_complete(self, eof: bool) -> bool:
        """Parse one response out of self.buf; resolve the waiter when
        complete. -> True when the waiter was resolved (result OR error)."""
        w = self.waiter
        buf = self.buf
        end = buf.find(b"\r\n\r\n")
        if end < 0:
            if eof:
                w.set_exception(
                    asyncio.IncompleteReadError(bytes(buf), None)
                )
                return True
            return False
        head = bytes(buf[:end])
        lower = head.lower()
        # any header-parse error must resolve the waiter, never escape
        # data_received/connection_lost (an escaped exception kills the
        # transport with the future left pending = request hangs forever)
        try:
            line_end = head.find(b"\r\n")
            if line_end < 0:
                line_end = len(head)  # head excludes the blank line's CRLF
            status = int(head[9:line_end].split(b" ", 1)[0] or 500)
            clen = -1
            chunked = b"transfer-encoding: chunked" in lower
            if not chunked:
                idx = lower.find(b"content-length:")
                if idx >= 0:
                    nl = lower.find(b"\r\n", idx)
                    if nl < 0:
                        nl = len(head)
                    clen = int(head[idx + 15: nl].strip())
        except ValueError:
            w.set_exception(ConnectionError("bad response head"))
            return True
        keep = b"connection: close" not in lower
        retry_after = None
        if status in (503, 429):
            # surface the peer's Retry-After so backoff/breakers honor
            # it — only parsed on shed statuses, the 200 path pays one
            # status compare
            idx = lower.find(b"retry-after:")
            if idx >= 0:
                nl = lower.find(b"\r\n", idx)
                if nl < 0:
                    nl = len(head)
                # delta-seconds or IMF-fixdate (ISSUE 12 satellite):
                # either spelling floors the backoff
                retry_after = parse_retry_after(head[idx + 12: nl].strip())
        if chunked:
            done = self._complete_chunked(end, status, keep, eof, retry_after)
        else:
            if clen >= 0:
                total = end + 4 + clen
                if len(buf) < total:
                    if eof:
                        w.set_exception(
                            asyncio.IncompleteReadError(bytes(buf), total)
                        )
                        return True
                    return False
                body = bytes(buf[end + 4: total])
                del buf[:total]
                w.set_result((status, body, keep, retry_after))
                done = True
            else:
                # length-less: framed by EOF, connection retired
                if not eof:
                    return False
                body = bytes(buf[end + 4:])
                del buf[:]
                w.set_result((status, body, False, retry_after))
                done = True
        if done:
            self.waiter = None
        return done

    def _complete_chunked(self, end, status, keep, eof, retry_after=None) -> bool:
        """Chunked responses re-walk the buffer per attempt: fine for this
        client's shapes (our servers Content-Length-frame the data plane;
        chunked replies are rare, small streams)."""
        buf = self.buf
        w = self.waiter
        pos = end + 4
        out = bytearray()
        while True:
            nl = buf.find(b"\r\n", pos)
            if nl < 0:
                break
            try:
                size = int(bytes(buf[pos:nl]).split(b";")[0].strip(), 16)
            except ValueError:
                w.set_exception(ConnectionError("bad chunk size"))
                return True
            if size == 0:
                tpos = nl + 2
                while True:
                    tnl = buf.find(b"\r\n", tpos)
                    if tnl < 0:
                        if eof:
                            w.set_exception(
                                asyncio.IncompleteReadError(bytes(buf), None)
                            )
                            return True
                        return False
                    if tnl == tpos:
                        del buf[:tnl + 2]
                        w.set_result((status, bytes(out), keep, retry_after))
                        return True
                    tpos = tnl + 2
            cstart = nl + 2
            cend = cstart + size
            if len(buf) < cend + 2:
                break
            out += buf[cstart:cend]
            pos = cend + 2
        if eof:
            w.set_exception(asyncio.IncompleteReadError(bytes(buf), None))
            return True
        return False


def _fire_timeout(conn: "_ClientConn", deadline_s: float) -> None:
    """Per-request deadline: fail the in-flight waiter and drop the
    connection (a half-read response can't be reused). Cheaper than
    wait_for on the hot path — one call_later handle, cancelled on the
    normal return."""
    w = conn.waiter
    if w is not None and not w.done():
        w.set_exception(
            TimeoutError(f"request exceeded {deadline_s}s deadline")
        )
    conn.closed = True
    if conn.transport is not None:
        conn.transport.close()


class FastHTTPClient:
    """Keep-alive HTTP/1.1 client pool. request() -> (status, body).

    Built for the data plane's shapes: small JSON/payload responses framed
    by Content-Length. Responses without a Content-Length are read to EOF
    and the connection retired.

    Overload-plane duties (ISSUE 9): every request carries a deadline
    (default 30s — no unbounded waits on the data plane; pass
    timeout=None ONLY for streaming shapes), consults the peer's circuit
    breaker (an open breaker raises CircuitOpenError in microseconds
    instead of burning the timeout), records the outcome into it, and
    surfaces 503/429 ``Retry-After`` hints via
    `retry_after_remaining(hostport)` so retry loops sleep at least as
    long as the peer asked."""

    def __init__(self, pool_per_host: int = 32):
        self._pool: dict = {}
        self._limit = pool_per_host
        self._breakers: dict = {}  # hostport -> CircuitBreaker | None
        self._retry_after: dict = {}  # hostport -> monotonic deadline

    def _breaker(self, hostport: str):
        try:
            return self._breakers[hostport]
        except KeyError:
            br = self._breakers[hostport] = overload.peer_breaker(hostport)
            return br

    def note_retry_after(self, hostport: str, seconds: float) -> None:
        self._retry_after[hostport] = time.monotonic() + seconds

    def retry_after_remaining(self, hostport: str) -> float:
        """Seconds the peer asked us to stay away (0 when none/expired)
        — retry loops pass this as retry_async's delay_floor."""
        t = self._retry_after.get(hostport)
        if t is None:
            return 0.0
        rem = t - time.monotonic()
        if rem <= 0:
            del self._retry_after[hostport]
            return 0.0
        return rem

    async def _get(
        self, hostport: str, timeout: Optional[float] = None
    ) -> _ClientConn:
        conns = self._pool.setdefault(hostport, [])
        while conns:
            c = conns.pop()
            if not c.closed and not c.transport.is_closing():
                return c
        host, _, port = hostport.rpartition(":")
        loop = asyncio.get_running_loop()
        # the request deadline covers connection establishment too: a
        # SYN-dropping peer (real partition, not the injected seam) must
        # fail within the caller's budget, not the OS connect timeout
        connect = loop.create_connection(
            lambda: _ClientConn(loop), host, int(port)
        )
        if timeout is not None:
            _, proto = await asyncio.wait_for(connect, timeout)
        else:
            _, proto = await connect
        return proto

    def _put(self, hostport: str, conn: _ClientConn):
        conns = self._pool.setdefault(hostport, [])
        if (
            len(conns) < self._limit
            and not conn.closed
            and not conn.transport.is_closing()
        ):
            conns.append(conn)
        else:
            conn.transport.close()

    async def request(
        self,
        method: str,
        hostport: str,
        target: str,
        body: bytes = b"",
        content_type: str = "",
        headers: Optional[dict] = None,
        retried: bool = False,
        timeout: Optional[float] = 30.0,
    ) -> tuple[int, bytes]:
        t0 = time.monotonic()
        br = self._breaker(hostport)
        if br is not None and not br.allow():
            raise overload.CircuitOpenError(
                f"circuit open to {hostport} (peer failing/shedding)"
            )
        plan = faults._PLAN
        if plan is not None:
            # fault-injection seam: latency sleeps, resets raise, and
            # http_error rules synthesize a 5xx as if the peer degraded
            try:
                ev = await faults.async_fault(
                    plan, f"http:{method}", hostport, timeout=timeout
                )
            except asyncio.CancelledError:
                # abandoned mid-sleep (hedge lost its race): no verdict
                # on the peer, but a held half-open probe slot must be
                # returned or the breaker wedges shut
                if br is not None:
                    br.record_cancelled()
                raise
            except Exception:
                if br is not None:
                    br.record_failure()
                raise
            if ev is not None and ev.kind == "http_error":
                # tail sampling: a trace that saw an injected fault is
                # kept (flag is a no-op without an active context)
                trace.flag(trace.FLAG_FAULT)
                if br is not None:
                    if ev.rule.status in (503, 429):
                        br.record_shed()
                    else:
                        # any other synthesized status still proves the
                        # peer answered — and a half-open probe MUST get
                        # an outcome here or it wedges the breaker open
                        # forever (allow() consumed the probe slot)
                        br.record_success()
                return ev.rule.status, b'{"error":"injected fault"}'
        # cross-hop context propagation: an active trace context rides a
        # `traceparent` header so the server side joins the same trace
        # (sampled or not — unsampled contexts still carry promotion
        # flags downstream). The ctx-less path pays one contextvar load.
        ctx = trace._CTX.get()
        # one logical request spends ONE deadline across all its phases:
        # the injected-fault wait above, connect, and the response below
        # are each armed with the REMAINING budget, never a fresh copy
        # of `timeout` (which would stack to ~3x the stated deadline)
        left = timeout
        if timeout is not None:
            left = max(0.001, timeout - (time.monotonic() - t0))
        try:
            conn = await self._get(hostport, left)
        except asyncio.CancelledError:
            if br is not None:
                br.record_cancelled()
            raise
        except (OSError, asyncio.TimeoutError) as e:
            # connect refused/timed out: the canonical dead-peer signal.
            # asyncio.TimeoutError (wait_for's connect deadline) is NOT
            # the builtin TimeoutError until 3.11, so it needs its own
            # arm here — and a translation, so callers catching
            # TimeoutError/OSError see the connect timeout too
            if br is not None:
                br.record_failure()
            if not isinstance(e, OSError):
                raise TimeoutError(
                    f"connect to {hostport} exceeded {timeout}s deadline"
                ) from e
            raise
        # cross-hop tenant propagation (ISSUE 12): a non-default current
        # tenant (set by ServingCore dispatch) rides the explicit header
        # so the downstream server's admission gate sees the SAME
        # principal the gateway derived. One contextvar load per
        # request, the trace-context pattern.
        tenant = _cur_tenant()
        if (
            not body and not content_type and not headers
            and method == "GET" and ctx is None and tenant is None
        ):
            # bodyless GET (the read data plane): one f-string render, no
            # part list/join — measurable at serving QPS rates
            wire = (
                f"GET {target} HTTP/1.1\r\nHost: {hostport}\r\n\r\n".encode()
            )
        else:
            parts = [
                f"{method} {target} HTTP/1.1\r\nHost: {hostport}\r\n".encode()
            ]
            if content_type:
                parts.append(f"Content-Type: {content_type}\r\n".encode())
            if body or method in ("POST", "PUT"):
                parts.append(b"Content-Length: %d\r\n" % len(body))
            if headers:
                for k, v in headers.items():
                    parts.append(f"{k}: {v}\r\n".encode())
            if ctx is not None:
                parts.append(
                    b"traceparent: %s\r\n"
                    % trace.format_traceparent_bytes(ctx)
                )
            if tenant is not None:
                parts.append(
                    b"X-Seaweed-Tenant: %s\r\n"
                    % tenant.encode("latin1", "replace")
                )
            parts.append(b"\r\n")
            if body:
                parts.append(body)
            wire = b"".join(parts)
        th = None
        try:
            fut = conn.begin()
            conn.transport.write(wire)
            if timeout is not None:
                left = max(0.001, timeout - (time.monotonic() - t0))
                th = conn._loop.call_later(
                    left, _fire_timeout, conn, left
                )
            status, resp_body, reusable, retry_after = await fut
        except asyncio.CancelledError:
            # a cancelled request (hedged read losing its race) leaves the
            # response half-read on the wire: the connection must die, not
            # linger open outside the pool — and a held half-open probe
            # slot must be returned, or the breaker wedges shut
            conn.transport.close()
            if br is not None:
                br.record_cancelled()
            raise
        except TimeoutError:
            # deadline fired (TimeoutError is an OSError since 3.10 —
            # this arm must come first): NOT retried, a fresh connection
            # would just burn another full deadline against a hung peer
            if br is not None:
                br.record_failure()
            raise
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            conn.transport.close()
            if retried:
                if br is not None:
                    br.record_failure()
                raise
            # stale pooled connection: one clean retry on a fresh one,
            # against the REMAINING deadline (one logical request never
            # exceeds its stated budget) — and a promotion flag, so the
            # trace that paid the retry is kept by the tail sampler
            if th is not None:
                th.cancel()
                th = None
            if br is not None:
                # a stale-connection write failure is no verdict on the
                # peer — but if this request holds the half-open probe
                # slot, the recursion's allow() would refuse it (and
                # leak the slot until its lease): hand it back first so
                # the retry becomes the probe
                br.record_cancelled()
            trace.flag(trace.FLAG_RETRY)
            left = timeout
            if timeout is not None:
                left = max(0.001, timeout - (time.monotonic() - t0))
            return await self.request(
                method, hostport, target, body, content_type, headers,
                retried=True, timeout=left,
            )
        finally:
            if th is not None:
                th.cancel()
        if reusable:
            self._put(hostport, conn)
        else:
            conn.transport.close()
        if status in (503, 429):
            if retry_after is not None:
                self.note_retry_after(hostport, retry_after)
            if br is not None:
                br.record_shed(retry_after)
        else:
            if br is not None:
                # any completed response (404s included) proves the peer
                # is up and admitting — only transport failures and
                # sheds count against it
                br.record_success()
            # every completed response is "successful traffic" for the
            # shared retry budget (the gRPC retry-throttling shape:
            # successes deposit ratio, failures withdraw 1 — so the
            # hedges/failovers this client's callers pay for stay capped
            # at a fraction of real throughput and refill as the system
            # heals, not only when a retry_async loop happens to run)
            bud = shared_retry_budget()
            if bud is not None:
                bud.on_success()
        return status, resp_body

    async def close(self):
        for conns in self._pool.values():
            for c in conns:
                try:
                    c.transport.close()
                except Exception:
                    pass
        self._pool.clear()


def build_multipart(
    field: str, data: bytes, filename: str = "file", mime: str = ""
) -> tuple[bytes, str]:
    """(body, content_type) for a single-part multipart/form-data upload."""
    boundary = "seaweedtpu-boundary-7f29a1"
    ct = f"Content-Type: {mime}\r\n" if mime else ""
    head = (
        f"--{boundary}\r\nContent-Disposition: form-data; "
        f'name="{field}"; filename="{filename}"\r\n{ct}\r\n'
    ).encode()
    tail = f"\r\n--{boundary}--\r\n".encode()
    return head + data + tail, f"multipart/form-data; boundary={boundary}"


def parse_multipart(body: bytes, content_type: bytes):
    """Single-pass parse of a multipart/form-data body: the first part
    whose disposition names file/upload (or carries a filename) ->
    (data, filename, mime) — or None when the shape is unexpected (caller
    falls back to the full parser). `data` is a zero-copy memoryview into
    `body` (the write fast path hands it straight to the needle append;
    callers that need bytes call bytes() on it)."""
    idx = content_type.find(b"boundary=")
    if idx < 0:
        return None
    boundary = content_type[idx + 9:].split(b";")[0].strip().strip(b'"')
    delim = b"--" + boundary
    pos = body.find(delim)
    while pos >= 0:
        pos += len(delim)
        if body[pos: pos + 2] == b"--":
            return None  # closing delimiter before a usable part
        head_start = pos + 2  # skip CRLF
        head_end = body.find(b"\r\n\r\n", head_start)
        if head_end < 0:
            return None
        head = body[head_start:head_end].lower()
        orig_head = body[head_start:head_end]
        data_start = head_end + 4
        nxt = body.find(b"\r\n" + delim, data_start)
        if nxt < 0:
            return None
        if (
            b'name="file"' in head
            or b'name="upload"' in head
            or b"filename=" in head
        ):
            filename = ""
            fi = orig_head.find(b"filename=")
            if fi >= 0:
                fn = orig_head[fi + 9:].split(b"\r\n")[0].split(b";")[0]
                filename = fn.strip().strip(b'"').decode("utf-8", "replace")
            mime = ""
            mi = head.find(b"content-type:")
            if mi >= 0:
                mime = (
                    orig_head[mi + 13:]
                    .split(b"\r\n")[0]
                    .strip()
                    .decode("latin1")
                )
            return memoryview(body)[data_start:nxt], filename, mime
        pos = body.find(delim, nxt)
    return None
