"""Tenant identity, weights, quotas, and the bounded-cardinality label
policy for the tenant QoS plane (ISSUE 12).

Every serving surface used to run one implicitly-shared queue per
priority class: PR 9's admission gates order by request *class* only, so
one abusive tenant's reads sit in the same CLASS_READ pool as everyone
else's and starve them wholesale (the cross-workload contention hazard
measured for shared EC storage in arXiv 1709.05365). This module is the
identity half of the fix — `util/overload.py` consumes it for
weighted-fair dequeue and per-tenant quotas:

- **Identity derivation** (`tenant_from_request`): one principal shared
  by master/volume/filer/S3. Priority order:

  1. an explicit ``X-Seaweed-Tenant`` header (raw-tier clients, and the
     header our own FastHTTPClient propagates across in-cluster hops so
     a request keeps its principal from the S3 gateway down to the
     volume server);
  2. the ``collection`` query parameter (filer/volume/master surfaces —
     collections are the reference's native multi-tenancy unit);
  3. server-specific hooks layered on top: the S3 gateway maps the V4
     ``Credential=`` access key to its IAM identity name, the volume
     server maps a read path's vid to the volume's collection.

  No signal -> the ``default`` tenant (exactly the pre-ISSUE-12
  behavior: one shared pool).

- **Weights** (`tenant_weight`): relative shares for the deficit-round-
  robin dequeue inside each admission class, parsed once from
  ``SEAWEEDFS_TPU_TENANT_WEIGHTS`` ("alice:4,bob:2", default 1.0,
  clamped to [0.1, 100] so the DRR rotation terminates in a bounded
  number of visits).

- **Quotas** (`TenantQuota`, `tenant_quota`): per-tenant token buckets
  for request rate (``SEAWEEDFS_TPU_TENANT_QPS``) and bytes/s
  (``SEAWEEDFS_TPU_TENANT_BPS``), both "name:value" lists where ``*``
  sets a default for every tenant. A dry bucket sheds with
  ``reason=quota`` — the same pre-rendered ~2µs 503 + Retry-After the
  overload gate already serves. Byte buckets are charged request-body
  bytes at admission and response bytes at release, and may go
  negative: a tenant that just pulled a huge object pays it off before
  admitting more bytes.

- **Label policy** (`TenantLabelPolicy`, `tenant_label`): metric label
  values for tenants are BOUNDED — the top-K tenants by decayed heat
  get their own label, everyone else collapses into ``other``
  (cardinality on a million-tenant box must not be a million series).
  The bound is enforced at the registry seam: at most ``cap`` admitted
  names + ``other`` + ``default`` ever render, and when a hotter
  tenant displaces a colder one the retired tenant's series are purged
  from the tenant-labeled families (our registry, our rules — a purge
  resets that tenant's counters, disclosed in docs/robustness.md).

The current tenant rides a contextvar (`set_current`/`current`) so the
filer's internal chunk uploads/reads carry the gateway's principal to
the volume tier; `util/fasthttp.FastHTTPClient` injects the header from
it the same way it injects ``traceparent``.
"""

from __future__ import annotations

import os
import time
from contextvars import ContextVar
from typing import Optional

DEFAULT_TENANT = "default"
OTHER_LABEL = "other"
TENANT_HEADER = "X-Seaweed-Tenant"
TENANT_HEADER_B = b"x-seaweed-tenant"

# current tenant principal for this task tree (None = default): set by
# ServingCore._dispatch for non-default principals, read by the HTTP
# client for cross-hop propagation. Module-bound get/set below keep the
# per-request cost at one contextvar load (the trace plane's pattern).
_TENANT: ContextVar[Optional[str]] = ContextVar("swfs_tenant", default=None)
current = _TENANT.get
set_current = _TENANT.set
reset_current = _TENANT.reset


def _parse_kv_env(name: str) -> dict:
    """Parse "a:1,b:2.5" env lists; malformed entries are dropped (an
    operator typo must not take the serving plane down at import)."""
    out: dict = {}
    raw = os.environ.get(name, "") or ""
    for part in raw.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        k, _, v = part.rpartition(":")
        try:
            out[k.strip()] = float(v)
        except ValueError:
            continue
    return out


class TenancyConfig:
    """Weights + quota rates, env-parsed once and overridable for tests
    and bench legs (`configure`)."""

    def __init__(self):
        self.reload()

    def reload(self) -> None:
        self.weights = _parse_kv_env("SEAWEEDFS_TPU_TENANT_WEIGHTS")
        self.qps = _parse_kv_env("SEAWEEDFS_TPU_TENANT_QPS")
        self.bps = _parse_kv_env("SEAWEEDFS_TPU_TENANT_BPS")

    def weight(self, tenant: str) -> float:
        w = self.weights.get(tenant)
        if w is None:
            w = self.weights.get("*", 1.0)
        # clamp: the DRR head-of-rotation top-up adds `weight` per visit
        # and serves at deficit >= 1, so weight >= 0.1 bounds the
        # rotation count before progress at 10
        return min(100.0, max(0.1, w))

    def quota_for(
        self, tenant: str, clock=time.monotonic
    ) -> Optional["TenantQuota"]:
        qps = self.qps.get(tenant, self.qps.get("*", 0.0))
        bps = self.bps.get(tenant, self.bps.get("*", 0.0))
        if qps <= 0.0 and bps <= 0.0:
            return None
        # the caller's clock (the gate's, possibly a test fake) drives
        # refills — a config-derived bucket on a different clock than
        # the gate that consults it would never refill under fakes
        return TenantQuota(qps=qps, byte_ps=bps, clock=clock)


CONFIG = TenancyConfig()


def configure(
    weights: Optional[dict] = None,
    qps: Optional[dict] = None,
    bps: Optional[dict] = None,
) -> None:
    """Install tenant weights/quota rates programmatically (tests, bench
    legs). Passing None for a field re-reads that field from env."""
    CONFIG.reload()
    if weights is not None:
        CONFIG.weights = dict(weights)
    if qps is not None:
        CONFIG.qps = dict(qps)
    if bps is not None:
        CONFIG.bps = dict(bps)


class TenantQuota:
    """Token buckets for one tenant: request rate + bytes/s.

    `burst_s` seconds of headroom; a rate of 0 disables that bucket.
    The byte bucket may go NEGATIVE (response sizes are only known at
    release), so `try_take` refuses while the tenant is paying off a
    prior burst. Single-event-loop use (the gate's discipline)."""

    __slots__ = (
        "qps", "byte_ps", "burst_s", "_rt", "_bt", "_last", "_clock"
    )

    def __init__(
        self,
        qps: float = 0.0,
        byte_ps: float = 0.0,
        burst_s: float = 1.0,
        clock=time.monotonic,
    ):
        self.qps = qps
        self.byte_ps = byte_ps
        self.burst_s = burst_s
        self._rt = qps * burst_s
        self._bt = byte_ps * burst_s
        self._clock = clock
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        dt = now - self._last
        if dt <= 0.0:
            return
        self._last = now
        if self.qps:
            self._rt = min(self.qps * self.burst_s, self._rt + dt * self.qps)
        if self.byte_ps:
            self._bt = min(
                self.byte_ps * self.burst_s, self._bt + dt * self.byte_ps
            )

    def try_take(self, cost_bytes: int = 0) -> bool:
        """One request (+ its known request-body bytes) against the
        buckets; False = over quota, shed with reason=quota. Both
        buckets are CHECKED before either is deducted — a refusal must
        not burn the request token of a request the dry byte bucket is
        about to refuse anyway."""
        self._refill()
        if self.qps and self._rt < 1.0:
            return False
        if self.byte_ps and self._bt <= 0.0:
            return False
        if self.qps:
            self._rt -= 1.0
        if self.byte_ps and cost_bytes:
            self._bt -= cost_bytes
        return True

    def charge_bytes(self, n: int) -> None:
        """Response bytes, charged at release (may drive the bucket
        negative — the next try_take refuses until it refills)."""
        if self.byte_ps and n:
            self._bt -= n

    def try_take_bytes(self, n: int) -> bool:
        """Byte-bucket-only consult + charge — no request token moves.
        The seam for traffic that is not a whole HTTP request of its
        own: a gRPC message's bytes, or one member's share of an
        admitted mixed-tenant batch frame. False = over byte quota."""
        self._refill()
        if self.byte_ps and self._bt <= 0.0:
            return False
        if self.byte_ps and n:
            self._bt -= n
        return True

    def refund_bytes(self, n: int) -> None:
        """Hand back bytes only (no request token): the carrier of a
        mixed-tenant batch was charged the whole frame at admission;
        each member's re-attribution returns the carrier's share."""
        if self.byte_ps and n:
            self._bt = min(self.byte_ps * self.burst_s, self._bt + n)

    def refill_horizon_s(self) -> float:
        """Seconds until the buckets refill to their fresh (full-burst)
        state. The gate's tenant-table prune only evicts a quota'd
        state after it has been idle at least this long: recreating the
        bucket then grants nothing natural refill would not have, so
        eviction cannot be exploited to erase byte debt."""
        self._refill()
        h = 0.0
        if self.qps:
            h = max(h, (self.qps * self.burst_s - self._rt) / self.qps)
        if self.byte_ps:
            h = max(
                h,
                (self.byte_ps * self.burst_s - self._bt) / self.byte_ps,
            )
        return h

    def refund(self, cost_bytes: int = 0) -> None:
        """Hand back one request token (+ its charged body bytes): the
        request was quota-charged at enqueue but never served (queue
        deadline, caller cancelled) — keeping the token would bill the
        tenant twice for one overload."""
        if self.qps:
            self._rt = min(self.qps * self.burst_s, self._rt + 1.0)
        if self.byte_ps and cost_bytes:
            self._bt = min(
                self.byte_ps * self.burst_s, self._bt + cost_bytes
            )

    def snapshot(self) -> dict:
        self._refill()
        return {
            "qps": self.qps,
            "byte_ps": self.byte_ps,
            "request_tokens": round(self._rt, 2),
            "byte_tokens": round(self._bt),
        }


# ------------------------------------------------- bounded label policy --


def _env_topk() -> int:
    try:
        return max(
            1, int(os.environ.get("SEAWEEDFS_TPU_TENANT_TOPK", "") or 16)
        )
    except ValueError:
        return 16


class TenantLabelPolicy:
    """Top-K-by-heat + ``other`` metric-label policy.

    `label(name)` returns `name` for at most `cap` distinct admitted
    tenants (plus the always-allowed ``default``), ``other`` for the
    rest — so tenant-labeled metric families hold <= cap + 2 distinct
    tenant values no matter how many principals a million-user box
    sees. Heat is a decayed per-tenant op count; when an unadmitted
    tenant's heat exceeds 2x the coldest admitted tenant's (hysteresis
    against label churn), the cold one is retired: its future ops label
    ``other`` and its existing series are PURGED from the registered
    tenant families via `on_retire` (the registry seam — purging is
    what keeps the cumulative distinct-value count bounded, not just
    the instantaneous one). Retirement checks are rate-limited to one
    per `swap_interval_s`."""

    def __init__(
        self,
        cap: Optional[int] = None,
        half_life_s: float = 60.0,
        swap_interval_s: float = 1.0,
        clock=time.monotonic,
        on_retire=None,
    ):
        self.cap = cap if cap is not None else _env_topk()
        self.half_life_s = half_life_s
        self.swap_interval_s = swap_interval_s
        self._clock = clock
        self.on_retire = on_retire
        self._heat: dict[str, float] = {}
        self._seen: dict[str, float] = {}  # last heat-update time
        self._admitted: set = set()
        self._last_swap = 0.0
        self.retired_total = 0

    def _decayed(self, name: str, now: float) -> float:
        h = self._heat.get(name, 0.0)
        t = self._seen.get(name, now)
        if h and now > t:
            h *= 0.5 ** ((now - t) / self.half_life_s)
        return h

    def note(self, name: str) -> None:
        """One op by `name` feeds its heat (fold-decayed in place)."""
        now = self._clock()
        self._heat[name] = self._decayed(name, now) + 1.0
        self._seen[name] = now
        if len(self._heat) > 8 * self.cap + 16:
            self._prune(now)

    def _prune(self, now: float) -> None:
        """Bound the heat table itself: keep admitted + the hottest
        non-admitted half (a million one-shot principals must not grow
        process memory without bound)."""
        keep = sorted(
            self._heat, key=lambda n: self._decayed(n, now), reverse=True
        )[: 4 * self.cap]
        keepset = set(keep) | self._admitted
        self._heat = {n: self._heat[n] for n in keepset if n in self._heat}
        self._seen = {n: self._seen[n] for n in keepset if n in self._seen}

    def label(self, name: str) -> str:
        if name == DEFAULT_TENANT or name in self._admitted:
            return name
        if len(self._admitted) < self.cap:
            self._admitted.add(name)
            return name
        now = self._clock()
        if now - self._last_swap >= self.swap_interval_s:
            self._last_swap = now
            mine = self._decayed(name, now)
            coldest = min(
                self._admitted, key=lambda n: self._decayed(n, now)
            )
            if mine > 2.0 * self._decayed(coldest, now):
                self._admitted.discard(coldest)
                self._admitted.add(name)
                self.retired_total += 1
                if self.on_retire is not None:
                    self.on_retire(coldest)
                return name
        return OTHER_LABEL

    def peek_label(self, name: str) -> str:
        """Non-mutating view of `label(name)` — status surfaces must not
        admit a tenant into the top-K as a side effect of rendering."""
        if name == DEFAULT_TENANT or name in self._admitted:
            return name
        return OTHER_LABEL

    def admitted(self) -> set:
        return set(self._admitted)


# bumped on every retirement purge: consumers caching per-label metric
# children (the admission gates) compare generations and drop their
# caches, or a cached child's next inc() would silently re-mint the
# purged series — and the caches themselves would grow with cumulative
# label churn instead of staying bounded by the live top-K
_PURGE_GEN = 0


def purge_generation() -> int:
    return _PURGE_GEN


def _purge_retired(name: str) -> None:
    """Registry-seam retirement: drop a retired tenant's series from
    every tenant-labeled family so the cumulative distinct-value count
    stays <= cap + 2 (counters restart at 0 if the tenant is ever
    re-admitted; the alternative is unbounded series growth)."""
    global _PURGE_GEN
    from . import metrics

    for fam in metrics.TENANT_LABELED_FAMILIES:
        fam.remove_label_value("tenant", name)
    _PURGE_GEN += 1


POLICY = TenantLabelPolicy(on_retire=_purge_retired)


def tenant_label(name: str) -> str:
    """The metric label value for a tenant principal (top-K + other)."""
    return POLICY.label(name)


def note_heat(name: str) -> None:
    """One op by `name` into the live policy's heat tracker (indirect on
    purpose: reset_policy swaps POLICY under long-lived callers)."""
    POLICY.note(name)


def reset_policy(cap: Optional[int] = None, **kw) -> None:
    """Fresh label policy (tests / bench legs). The OLD policy's
    admitted labels are purged first: a swap that abandoned them would
    leave series no retirement can ever reach — permanently stale
    cardinality that breaks the cumulative cap invariant (and made the
    test suite order-dependent before this purge existed). Live
    counters of currently-admitted tenants restart; acceptable for a
    test/bench hook."""
    global POLICY
    for name in POLICY.admitted():
        _purge_retired(name)
    POLICY = TenantLabelPolicy(cap=cap, on_retire=_purge_retired, **kw)


# ------------------------------------------------------------ derivation --


def tenant_from_request(req) -> Optional[str]:
    """Default fast-tier derivation: explicit header, else collection
    query parameter, else None (-> default tenant). `req` is a
    util/fasthttp.FastRequest (lower-cased byte header names)."""
    t = req.headers.get(TENANT_HEADER_B)
    if t:
        return t.decode("latin1")
    q = req.query
    if q:
        idx = q.find("collection=")
        while idx >= 0:
            # parameter-boundary guard — but keep SCANNING past a
            # rejected hit: "?mycollection=a&collection=beta" must find
            # the real parameter, not give up on the substring inside
            # "mycollection="
            if idx == 0 or q[idx - 1] == "&":
                end = q.find("&", idx)
                val = q[idx + 11: end if end >= 0 else len(q)]
                if val:
                    return val
            idx = q.find("collection=", idx + 1)
    return None
