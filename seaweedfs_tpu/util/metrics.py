"""Prometheus-style metrics registry (ref: weed/stats/metrics.go:15-93).

Counters, gauges and histograms with label support, rendered in the
Prometheus text exposition format at /metrics on each server. No external
client library; the push-gateway mode of the reference is replaced by pull.

Histogram bucket samples can carry OpenMetrics-style exemplars (the last
sampled trace_id observed per bucket, see util/trace.py): a `/metrics`
latency spike links straight to the trace that caused it in
`/debug/traces`. Exemplars are only emitted when `render(exemplars=True)`
is asked for — /metrics negotiates via the Accept header, because the
classic text format (text/plain) does not permit them and a stock
Prometheus scraper would reject the whole exposition.
"""

from __future__ import annotations

import threading
import time as _time
from bisect import bisect_left
from collections import defaultdict

_DEFAULT_BUCKETS = [
    0.0001, 0.0003, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1, 3, 10,
]

# set by util/trace.py at import: () -> hex trace id of the current
# SAMPLED context, or None. Kept as a module attribute (not an import) so
# the metrics module stays dependency-free at the bottom of the stack.
_exemplar_fn = None


class _Labeled:
    def __init__(self, name: str, help_text: str, kind: str):
        self.name = name
        self.help = help_text
        self.kind = kind
        self._lock = threading.Lock()

    def _series_dicts(self) -> list:
        """Every key->value store holding per-label-set series (the
        subclass's own dicts); remove_label_value edits them in place."""
        return []

    def remove_label_value(self, label: str, value: str) -> int:
        """Drop every series whose `label` equals `value` — the registry
        seam the bounded tenant-label policy uses to retire a displaced
        tenant's series (util/tenancy.TenantLabelPolicy): cumulative
        label cardinality stays capped only if retired values stop
        rendering. Returns the number of series dropped."""
        pair = (label, str(value))
        dropped = 0
        with self._lock:
            for d in self._series_dicts():
                for key in [k for k in d if _key_has(k, pair)]:
                    del d[key]
                    dropped += 1
        return dropped


def _key_has(key, pair) -> bool:
    """Does a label-set key (possibly (key, idx)-wrapped for exemplars)
    contain the (label, value) pair?"""
    if key and isinstance(key[0], tuple) and key[0] and isinstance(
        key[0][0], tuple
    ):
        key = key[0]  # histogram exemplar key: ((labels...), bucket_idx)
    return pair in key


class Counter(_Labeled):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "counter")
        self._values: dict[tuple, float] = defaultdict(float)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += amount

    def child(self, **labels) -> "_CounterChild":
        """Pre-bound label set with O(1) inc — for per-request hot paths
        where tuple(sorted(labels.items())) per call is measurable."""
        return _CounterChild(self, tuple(sorted(labels.items())))

    def _series_dicts(self) -> list:
        return [self._values]

    def render(self, exemplars: bool = False) -> list[str]:
        out = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} counter",
        ]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class _CounterChild:
    __slots__ = ("_counter", "_key")

    def __init__(self, counter: Counter, key: tuple):
        self._counter = counter
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        c = self._counter
        with c._lock:
            c._values[self._key] += amount


class Gauge(_Labeled):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "gauge")
        self._values: dict[tuple, float] = defaultdict(float)

    def set(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = value

    def add(self, amount: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] += amount

    def remove(self, **labels) -> None:
        """Drop ONE series (exact label set). A gauge whose label value
        has been retired by the bounded tenant policy must disappear,
        not be set to 0 — a 0 still renders and re-mints the purged
        series."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values.pop(key, None)

    def _series_dicts(self) -> list:
        return [self._values]

    def render(self, exemplars: bool = False) -> list[str]:
        out = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
        ]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self.name}{_fmt_labels(key)} {v}")
        return out


class Histogram(_Labeled):
    def __init__(self, name: str, help_text: str = "", buckets=None):
        super().__init__(name, help_text, "histogram")
        self.buckets = list(buckets or _DEFAULT_BUCKETS)
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = defaultdict(float)
        self._totals: dict[tuple, int] = defaultdict(int)
        # (key, bucket_idx) -> (trace_hex, observed value, unix ts): the
        # last SAMPLED observation per bucket, written only when the
        # tracing contextvar says the current request is sampled — the
        # unsampled hot path pays one module-attribute load + None check
        self._exemplars: dict[tuple, tuple] = {}

    def observe(self, value: float, **labels) -> None:
        key = tuple(sorted(labels.items()))
        self._observe_key(key, value)

    def _observe_key(self, key: tuple, value: float) -> None:
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            # Prometheus le bounds are INCLUSIVE: a value equal to a
            # boundary belongs in that boundary's bucket (bisect_left).
            # bisect_right pushed every exact boundary hit one bucket up —
            # invisible for continuous latencies, wrong for the integer
            # batch-size buckets where boundary values are the common case
            idx = bisect_left(self.buckets, value)
            if idx < len(counts):
                counts[idx] += 1  # cumulative sums computed at render time
            self._sums[key] += value
            self._totals[key] += 1
        fn = _exemplar_fn
        if fn is not None:
            tid = fn()
            if tid is not None:
                self._exemplars[(key, idx)] = (tid, value, _time.time())

    def child(self, **labels) -> "_HistogramChild":
        """Pre-bound label set with an O(1)-overhead observe — the
        histogram analogue of Counter.child, for per-request hot paths."""
        return _HistogramChild(self, tuple(sorted(labels.items())))

    def _series_dicts(self) -> list:
        return [self._counts, self._sums, self._totals, self._exemplars]

    def sum_count(self, **labels) -> tuple:
        """(sum, count) snapshot for one label set — bench legs
        difference these across a measured window to get per-stage
        averages without parsing the rendered exposition."""
        key = tuple(sorted(labels.items()))
        with self._lock:
            return self._sums.get(key, 0.0), self._totals.get(key, 0)

    def _exemplar_suffix(self, key: tuple, idx: int) -> str:
        ex = self._exemplars.get((key, idx))
        if ex is None:
            return ""
        tid, value, ts = ex
        # OpenMetrics exemplar syntax — emitted only for the negotiated
        # application/openmetrics-text exposition (see Registry.render)
        return ' # {trace_id="%s"} %g %.3f' % (tid, value, ts)

    def render(self, exemplars: bool = False) -> list[str]:
        out = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        ex = self._exemplar_suffix if exemplars else (lambda key, i: "")
        with self._lock:
            for key, counts in self._counts.items():
                cumulative = 0
                for i, (b, c) in enumerate(zip(self.buckets, counts)):
                    cumulative += c
                    out.append(
                        f"{self.name}_bucket{_fmt_labels(key, le=str(b))} "
                        f"{cumulative}{ex(key, i)}"
                    )
                out.append(
                    f'{self.name}_bucket{_fmt_labels(key, le="+Inf")} '
                    f"{self._totals[key]}"
                    f"{ex(key, len(self.buckets))}"
                )
                out.append(f"{self.name}_sum{_fmt_labels(key)} {self._sums[key]}")
                out.append(f"{self.name}_count{_fmt_labels(key)} {self._totals[key]}")
        return out


class _HistogramChild:
    __slots__ = ("_hist", "_key")

    def __init__(self, hist: Histogram, key: tuple):
        self._hist = hist
        self._key = key

    def observe(self, value: float) -> None:
        self._hist._observe_key(self._key, value)


def _escape_label_value(v) -> str:
    """Escape per the exposition-format spec: backslash, double-quote and
    newline inside a label value must be escaped or the whole render is
    unparseable (vacuum route labels and fault `op` labels can carry
    arbitrary strings)."""
    s = str(v)
    if "\\" in s or '"' in s or "\n" in s:
        s = s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return s


def _escape_help(s: str) -> str:
    """HELP lines escape backslash and newline (spec: help text is the
    rest of the line)."""
    if "\\" in s or "\n" in s:
        s = s.replace("\\", "\\\\").replace("\n", "\\n")
    return s


def _fmt_labels(key: tuple, **extra) -> str:
    items = list(key) + sorted(extra.items())
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + inner + "}"


class Registry:
    """Name-keyed metric registry. Registration is idempotent: asking for
    an existing name returns the existing collector when the kind
    matches, and raises when it doesn't — duplicate metric families can
    never render (they are invalid exposition text, and the silent
    variant hid typo'd re-registrations)."""

    def __init__(self):
        self._metrics: list = []
        self._by_name: dict[str, _Labeled] = {}
        self._lock = threading.Lock()

    def _register(self, name: str, kind: str, factory):
        with self._lock:
            m = self._by_name.get(name)
            if m is not None:
                if m.kind != kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"not {kind}"
                    )
                return m
            m = factory()
            self._by_name[name] = m
            self._metrics.append(m)
            return m

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._register(
            name, "counter", lambda: Counter(name, help_text)
        )

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._register(name, "gauge", lambda: Gauge(name, help_text))

    def histogram(self, name: str, help_text: str = "", buckets=None) -> Histogram:
        m = self._register(
            name, "histogram", lambda: Histogram(name, help_text, buckets)
        )
        if buckets is not None and list(buckets) != m.buckets:
            # idempotent return must not silently change bucket layout:
            # observations from the second site would land in the first
            # site's buckets and render wrong percentiles with no error
            raise ValueError(
                f"metric {name!r} already registered with buckets "
                f"{m.buckets}, not {list(buckets)}"
            )
        return m

    def collectors(self) -> list:
        """Snapshot of registered metrics (hygiene lint / self-checks)."""
        with self._lock:
            return list(self._metrics)

    def render(self, exemplars: bool = False) -> str:
        """Text exposition. `exemplars=True` appends the OpenMetrics
        exemplar suffix to histogram bucket samples — only valid under
        the `application/openmetrics-text` content type (classic
        text-format parsers reject a `#` after the sample value), so
        /metrics serves it via Accept-header negotiation only."""
        lines = []
        with self._lock:
            for m in self._metrics:
                lines.extend(m.render(exemplars=exemplars))
        return "\n".join(lines) + "\n"


# global registry + the server metric families the reference defines
REGISTRY = Registry()

REQUEST_COUNTER = REGISTRY.counter(
    "seaweedfs_tpu_request_total", "number of requests by server/operation"
)
REQUEST_HISTOGRAM = REGISTRY.histogram(
    "seaweedfs_tpu_request_seconds", "request latency by server/operation"
)
VOLUME_GAUGE = REGISTRY.gauge(
    "seaweedfs_tpu_volumes", "volumes/ec-shards served per collection"
)
EC_ENCODE_BYTES = REGISTRY.counter(
    "seaweedfs_tpu_ec_encoded_bytes_total", "bytes erasure-coded, by backend"
)

# degraded-mode visibility (see docs/robustness.md): every retry loop,
# on-the-fly EC reconstruction and load-time torn-tail repair counts here,
# so a chaos run can assert HOW the system survived, not just that it did
RETRY_COUNTER = REGISTRY.counter(
    "seaweedfs_tpu_retries_total", "retry attempts by operation"
)
EC_RECONSTRUCTIONS = REGISTRY.counter(
    "seaweedfs_tpu_ec_reconstructions_total",
    "EC intervals served by reconstruction from >= data_shards other shards, "
    'by kind (kind="cold" = full survivor fetch + decode, kind="cache_hit" '
    "= served from the degraded-read interval cache)",
)
TORN_TAIL_COUNTER = REGISTRY.counter(
    "seaweedfs_tpu_torn_tail_total",
    "torn-tail recovery on volume load, by item "
    "(volumes/records_recovered/dat_bytes_dropped/idx_entries_dropped)",
)
FAULTS_INJECTED = REGISTRY.counter(
    "seaweedfs_tpu_faults_injected_total",
    "faults fired by the active injection plan, by op/kind",
)

# serving-plane write-path attribution (see docs/perf.md): stages of one
# replicated/fsync'd POST — local_append (append[+fsync] wall),
# replicate_wait (extra wall the ack spent on the fan-out AFTER the local
# write finished; overlap means this shrinks toward 0), group_commit_wait
# (enqueue -> fsync'd-batch-resolution wall on the fsync=true tier)
WRITE_STAGE_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_write_stage_seconds",
    "volume write path stage wall time, by stage",
)
GROUP_COMMIT_BATCH_SIZE = REGISTRY.histogram(
    "seaweedfs_tpu_group_commit_batch_size",
    "requests per group-commit fsync batch",
    buckets=[1, 2, 4, 8, 16, 32, 64, 128],
)
GROUP_COMMIT_FSYNCS = REGISTRY.counter(
    "seaweedfs_tpu_group_commit_fsyncs_total",
    "group-commit batches flushed (one fsync each)",
)

# serving read plane (see docs/perf.md "Serving read plane"): the read
# path gets the same itemized-stage treatment as writes, and the
# hot-needle cache in front of the volume tier is externally auditable —
# hit rate, bytes it absorbed, and the LRU's churn
READ_STAGE_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_read_stage_seconds",
    "volume read path stage wall time, by stage (cache_hit = full request "
    "served from the hot-needle cache; read_render = map probe + pread + "
    "parse + response render on a miss)",
)
READ_CACHE_HITS = REGISTRY.counter(
    "seaweedfs_tpu_read_cache_hits_total",
    "reads served whole from the hot-needle cache",
)
READ_CACHE_MISSES = REGISTRY.counter(
    "seaweedfs_tpu_read_cache_misses_total",
    "cacheable reads that went to the volume tier (includes entries "
    "invalidated by overwrite/delete/vacuum since they were cached)",
)
READ_CACHE_BYTES = REGISTRY.counter(
    "seaweedfs_tpu_read_cache_bytes_total",
    "response bytes served from the hot-needle cache",
)
READ_CACHE_EVICTIONS = REGISTRY.counter(
    "seaweedfs_tpu_read_cache_evictions_total",
    "hot-needle cache entries evicted (LRU byte bound) or invalidated "
    "(overwrite/delete/vacuum-commit), by reason",
)

# repair-plane attribution (see docs/perf.md "Repair plane"): rebuild gets
# the same itemized-budget treatment the write path got — per-stage walls
# of every rebuild_ec_files run (stages overlap on the pipelined route, so
# their sum can exceed the rebuild wall), degraded-read interval latency
# split cold vs cache-served, and the decode-matrix LRU's hit rate
EC_REBUILD_STAGE_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_ec_rebuild_stage_seconds",
    "rebuild_ec_files per-stage wall seconds, by stage (read/decode/write; "
    "pipelined stages overlap)",
)
EC_DEGRADED_READ_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_ec_degraded_read_seconds",
    "degraded EC interval read latency, by result (cold/cache_hit)",
)
EC_DECODE_MATRIX_CACHE = REGISTRY.counter(
    "seaweedfs_tpu_ec_decode_matrix_cache_total",
    "decode-matrix LRU lookups, by outcome (hit/miss)",
)

# anti-entropy plane (see docs/robustness.md "Anti-entropy plane"): the
# background scrub proves stored bytes still verify, replica digests catch
# diverged/stale copies, and the master's repair scheduler turns both into
# rebuilds/resyncs — each stage observable so a chaos run can assert the
# loop closed (corruption found -> repaired -> re-scrub clean)
SCRUB_BYTES = REGISTRY.counter(
    "seaweedfs_tpu_scrub_bytes_total",
    "bytes read and verified by the scrubber, by kind (dat/idx/ec)",
)
SCRUB_CORRUPTIONS = REGISTRY.counter(
    "seaweedfs_tpu_scrub_corruptions_found_total",
    "latent damage found by scrub, by kind (needle_crc/needle_id/"
    "idx_extent/ec_data/ec_parity/ec_shard_size/ec_unidentified)",
)
SCRUB_PASSES = REGISTRY.counter(
    "seaweedfs_tpu_scrub_passes_total",
    "completed scrub passes, by plane (volume/ec)",
)
ANTIENTROPY_RESYNCS = REGISTRY.counter(
    "seaweedfs_tpu_antientropy_resyncs_total",
    "replica repairs dispatched by digest/scrub anti-entropy, by kind "
    "(tail_sync = catch-up append replay, recopy = full re-pull of a "
    "quarantined replica)",
)
REPAIR_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_tpu_repair_queue_depth",
    "repair tasks currently queued on the master (fewest-survivors-first)",
)
ANTIENTROPY_DIVERGED = REGISTRY.gauge(
    "seaweedfs_tpu_antientropy_diverged_volumes",
    "volumes whose healthy replicas disagree on content digest with EQUAL "
    "append frontiers — divergence the tail path cannot fix (operator "
    "action: volume.fsck / re-replicate); refreshed every scheduler scan",
)
REPAIR_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_repair_seconds",
    "wall seconds per dispatched repair, by kind (ec_rebuild/replica_"
    "recopy/tail_sync/vacuum) and result (ok/error/skipped)",
)

# geo plane (see docs/robustness.md "Geo plane"): DC/rack-aware placement
# violations found by the master's anti-entropy scan, and the cross-cluster
# async replicator's applied/skipped/retried ledger + lag — the observable
# core of the "bounded-lag, zero-loss/zero-dup after heal" SLO
PLACEMENT_VIOLATIONS = REGISTRY.gauge(
    "seaweedfs_tpu_placement_violations",
    "volumes/EC volumes whose current holders violate placement policy, "
    "by kind (replica_spread = replicas packed below the ReplicaPlacement "
    "rack/DC spread, ec_domain = one failure domain holds more EC shards "
    "than the volume can lose); refreshed every anti-entropy scan",
)
GEO_EVENTS_APPLIED = REGISTRY.counter(
    "seaweedfs_tpu_geo_events_applied_total",
    "meta-log events applied on the peer cluster by the geo replicator, "
    "by type (create/update/delete/rename)",
)
GEO_EVENTS_SKIPPED = REGISTRY.counter(
    "seaweedfs_tpu_geo_events_skipped_total",
    "meta-log events the geo replicator skipped, by reason (dup = "
    "idempotency key already applied — the kill/restart replay shield, "
    "stale = behind the durable cursor, internal = bookkeeping paths)",
)
GEO_EVENTS_RETRIED = REGISTRY.counter(
    "seaweedfs_tpu_geo_events_retried_total",
    "geo replicator apply attempts that failed and were retried (WAN "
    "partition / peer outage shows up here, never as a skipped event)",
)
GEO_REPLICATION_LAG = REGISTRY.histogram(
    "seaweedfs_tpu_geo_replication_lag_seconds",
    "age of each applied event at apply time (primary append -> peer "
    "apply); p99 is the replication-lag SLO the soak scores",
    buckets=[0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300],
)
GEO_FULL_RESYNC_REQUIRED = REGISTRY.counter(
    "seaweedfs_tpu_geo_full_resync_required_total",
    "times the replicator's cursor fell behind the primary meta-log "
    "retention (MetaLogTrimmed): events in the hole can never stream; "
    "the replicator halts LOUDLY and requires an operator full resync — "
    "it never silently skips the gap",
)

# object gateway (see docs/perf.md "Object gateway"): the S3/filer fast
# path gets the same itemized-stage treatment as the volume write path —
# every fast-tier PutObject partitions its handler wall into
# auth/meta/lease/upload/render (GETs into auth/meta/fetch/render), and
# the LIST path discloses how many store entries each request actually
# scanned (the O(max-keys)-not-O(bucket) claim, externally auditable)
S3_STAGE_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_s3_stage_seconds",
    "S3 gateway fast-path stage wall seconds, by verb and stage (PUT: "
    "auth/meta/lease/upload/render partition the handler wall; GET: "
    "auth/meta/fetch/render)",
)
S3_LIST_SCANNED = REGISTRY.counter(
    "seaweedfs_tpu_s3_list_scanned_entries_total",
    "filer-store entries pulled by ListObjects range scans (per-request "
    "work bound: O(max-keys + returned CommonPrefixes))",
)
S3_LIST_REQUESTS = REGISTRY.counter(
    "seaweedfs_tpu_s3_list_requests_total",
    "ListObjects requests served by the range-scan path",
)
CHUNK_BATCH_PUT_SIZE = REGISTRY.histogram(
    "seaweedfs_tpu_chunk_batch_put_size",
    "needles per batched fast-tier chunk PUT (POST /!batch/put — the "
    "filer upload gate's same-tick coalescing width)",
    buckets=[1, 2, 4, 8, 16, 32, 64],
)
FILER_CHUNK_DELETE_BATCHES = REGISTRY.counter(
    "seaweedfs_tpu_filer_chunk_delete_batches_total",
    "batched per-host chunk-delete RPC rounds drained by the filer GC, "
    "by result (ok/retry)",
)

# vacuum plane (see docs/perf.md "Vacuum plane"): compaction gets the same
# itemized treatment as the rebuild plane — per-stage walls of every
# extent-coalesced copy (pipelined read overlaps write, so stage sums can
# exceed total), the master's garbage-driven queue depth, and the shared
# maintenance budget's per-plane spend so the combined background I/O cap
# is externally auditable
VACUUM_STAGE_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_vacuum_stage_seconds",
    "compaction copy per-stage wall seconds, by stage (plan/read/write/"
    "verify/idx/total; pipelined stages overlap)",
)
VACUUM_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_tpu_vacuum_queue_depth",
    "vacuum tasks currently queued on the master (highest-garbage-first)",
)
MAINTENANCE_BYTES = REGISTRY.counter(
    "seaweedfs_tpu_maintenance_bytes_total",
    "bytes charged to the shared maintenance I/O budget, by plane "
    "(scrub/vacuum/repair)",
)

# overload control plane (see docs/robustness.md "Overload plane"): every
# admission decision, limit move, breaker transition and suppressed retry
# is counted so a brownout/overload run can assert HOW goodput survived —
# lowest-class-first shedding, breakers isolating the sick peer, retries
# capped at a fraction of successes — not just that it did
OVERLOAD_SHED = REGISTRY.counter(
    "seaweedfs_tpu_overload_shed_total",
    "requests shed by the admission gate, by server, priority class "
    "(read/write/meta/maint), tenant (top-K by heat + 'other' — see "
    "docs/robustness.md Tenant QoS) and reason (deadline = waited past "
    "the class's queue budget, queue_full = class's queue share "
    "exhausted, quota = tenant rate/byte token bucket dry)",
)
ADMISSION_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_tpu_admission_queue_depth",
    "requests queued behind the adaptive concurrency limit, per server",
)
ADMISSION_LIMIT = REGISTRY.gauge(
    "seaweedfs_tpu_admission_limit",
    "live adaptive concurrency limit (AIMD on latency vs baseline), "
    "per server",
)
RETRIES_SUPPRESSED = REGISTRY.counter(
    "seaweedfs_tpu_retries_suppressed_total",
    "retries/hedges withheld by the shared RetryBudget (token bucket "
    "refilled by successes — no retry storms), by op",
)
CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "seaweedfs_tpu_circuit_transitions_total",
    "circuit-breaker state transitions, by peer and target state",
)
CIRCUIT_OPEN = REGISTRY.gauge(
    "seaweedfs_tpu_circuit_open",
    "1 while a peer's circuit breaker is open (calls fail fast)",
)
MAINTENANCE_YIELDS = REGISTRY.counter(
    "seaweedfs_tpu_maintenance_pressure_yields_total",
    "maintenance budget consumes that yielded extra time to foreground "
    "pressure (admission gates shedding/queueing), by plane",
)

# lifecycle plane (see docs/perf.md "Lifecycle plane"): the hot→warm arc
# made observable — per-server aggregate access heat as sampled into
# heartbeats, the master's conversion queue depth, and every conversion
# the planner dispatched counted by direction and outcome, so an
# operator (and the bench's convergence leg) can assert the loop ran,
# drained, and did not flap
VOLUME_HEAT = REGISTRY.gauge(
    "seaweedfs_tpu_volume_heat",
    "per-server aggregate decayed access heat, by kind (read/write = "
    "normal volumes, ec_read = EC volumes); refreshed at the heartbeat "
    "digest tick",
)
LIFECYCLE_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_tpu_lifecycle_queue_depth",
    "lifecycle conversion tasks currently queued on the master "
    "(coldest-first for auto-EC, hottest-first for re-inflation)",
)
LIFECYCLE_CONVERSIONS = REGISTRY.counter(
    "seaweedfs_tpu_lifecycle_conversions_total",
    "lifecycle conversions dispatched by the master planner, by "
    "direction (ec = hot→warm auto-encode, inflate = warm→hot "
    "re-inflation) and result (ok/error/skipped)",
)

# tenant QoS plane (see docs/robustness.md "Tenant QoS"): per-tenant
# admission visibility with BOUNDED label cardinality — tenant label
# values pass through util/tenancy.tenant_label (top-K by decayed heat +
# 'other'; retired tenants' series are purged via remove_label_value at
# the registry seam), so these families stay <= K+2 tenant values no
# matter how many principals the box serves
TENANT_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_tpu_tenant_queue_depth",
    "requests queued behind the admission limit per tenant subqueue "
    "(deficit-round-robin within each priority class), by server, gate "
    "and tenant (top-K + other)",
)
TENANT_ADMITTED = REGISTRY.counter(
    "seaweedfs_tpu_tenant_admitted_total",
    "requests admitted by the gate per tenant (top-K + other), by "
    "server and tenant",
)
TENANT_ADMITTED_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_tenant_admitted_seconds",
    "server-side latency (admission wait + service) of admitted "
    "requests per tenant (top-K + other), by server and tenant",
)

# needle-index-at-scale plane (see docs/perf.md "Needle index at
# scale"): the out-of-core LSM needle map's memory story and mount
# behavior made observable — resident memtable bytes (the bound the map
# enforces), run counts (compaction health), how stale the snapshot a
# mount consumed was, and how many tail entries it had to replay past
# the fold frontier (the O(tail) claim, measurable in production)
NEEDLE_MAP_RESIDENT_BYTES = REGISTRY.gauge(
    "seaweedfs_tpu_needle_map_resident_bytes",
    "estimated resident memory held by needle-map memtables on this "
    "server, by map kind (the LSM map's byte bound; runs are mmap'd "
    "page cache and excluded on purpose)",
)
NEEDLE_MAP_RUN_COUNT = REGISTRY.gauge(
    "seaweedfs_tpu_needle_map_run_count",
    "immutable sorted runs currently backing needle maps on this "
    "server, by map kind (tiered merges keep this bounded)",
)
NEEDLE_MAP_SNAPSHOT_AGE = REGISTRY.gauge(
    "seaweedfs_tpu_needle_map_snapshot_age_seconds",
    "age of the persisted needle-map snapshot the most recent mount "
    "loaded, by map kind (how far behind the fold frontier was)",
)
NEEDLE_MAP_TAIL_REPLAY = REGISTRY.counter(
    "seaweedfs_tpu_needle_map_tail_replay_entries_total",
    "index entries replayed past the snapshot fold frontier at mount "
    "(the O(tail) mount cost actually paid)",
)

# metadata device-kernel plane (ISSUE 18, see docs/perf.md "Metadata
# device kernel"): the ragged-batch lookup arena made observable —
# what is pinned HBM-resident, how often whole gate wakeups run as one
# device dispatch vs fall back to host maps, and the identity-check
# verdicts that keep the arena an accelerator rather than an authority
NEEDLE_MAP_DEVICE_RESIDENT = REGISTRY.gauge(
    "seaweedfs_tpu_needle_map_device_resident_bytes",
    "bytes of sealed-run index columns pinned device-resident by the "
    "current DeviceColumnArena generation (LRU-bounded by "
    "SEAWEEDFS_TPU_ARENA_MB)",
)
NEEDLE_MAP_DEVICE_SEGMENTS = REGISTRY.gauge(
    "seaweedfs_tpu_needle_map_device_segments",
    "sealed segments resident in the current DeviceColumnArena "
    "generation (needle-map runs and filer path-spine segments share "
    "one arena)",
)
NEEDLE_MAP_DEVICE_DISPATCHES = REGISTRY.counter(
    "seaweedfs_tpu_needle_map_device_dispatches_total",
    "ragged-batch lookup dispatches answered on the device (one per "
    "gate wakeup routed to the arena, regardless of how many volumes "
    "or spine chains it spanned)",
)
NEEDLE_MAP_DEVICE_PROBES = REGISTRY.counter(
    "seaweedfs_tpu_needle_map_device_probes_total",
    "(key, segment) probe slots answered by ragged device dispatches "
    "(a key probing a 4-run volume counts 4)",
)
NEEDLE_MAP_DEVICE_FALLBACKS = REGISTRY.counter(
    "seaweedfs_tpu_needle_map_device_fallbacks_total",
    "gate flushes served by the host maps instead of the arena, by "
    "reason (cold arena, device absent, arena killed, oversize "
    "offsets)",
)
NEEDLE_MAP_DEVICE_UPLOADS = REGISTRY.counter(
    "seaweedfs_tpu_needle_map_device_uploads_total",
    "double-buffered arena generation uploads completed (each builds "
    "the next resident set while the previous keeps serving)",
)
NEEDLE_MAP_DEVICE_EVICTIONS = REGISTRY.counter(
    "seaweedfs_tpu_needle_map_device_evictions_total",
    "segments denied residency by the arena's LRU byte budget at a "
    "generation refresh",
)
NEEDLE_MAP_DEVICE_IDENTITY_MISMATCH = REGISTRY.counter(
    "seaweedfs_tpu_needle_map_device_identity_mismatch_total",
    "device answers that disagreed with the host map under the "
    "identity check (the host answer is served; any non-zero value is "
    "a kernel bug)",
)

# cold-tier plane (ISSUE 14, see docs/perf.md "Cold tier"): the
# hot→warm→cold arc's third band made observable — bytes moved between
# local disk and the remote backend by direction, per-holder recall
# walls (the latency a reheating volume pays before it is local again),
# and the remote read-through cache's hit economics (each miss is one
# ranged remote GET)
TIER_OFFLOAD_BYTES = REGISTRY.counter(
    "seaweedfs_tpu_tier_offload_bytes_total",
    "EC shard bytes moved between local disk and the remote cold-tier "
    "backend, by direction (offload = local→remote, recall = "
    "remote→local)",
)
TIER_RECALL_SECONDS = REGISTRY.histogram(
    "seaweedfs_tpu_tier_recall_seconds",
    "wall seconds one holder spent recalling a volume's offloaded "
    "shards back to local disk (download + rename + manifest commit + "
    "remote delete, per VolumeEcShardsRecall)",
)
TIER_REMOTE_CACHE_HITS = REGISTRY.counter(
    "seaweedfs_tpu_tier_remote_cache_hits_total",
    "reads of offloaded EC shards served from the byte-range "
    "read-through cache (no remote round trip)",
)
TIER_REMOTE_CACHE_MISSES = REGISTRY.counter(
    "seaweedfs_tpu_tier_remote_cache_misses_total",
    "reads of offloaded EC shards that paid a ranged remote GET "
    "(readahead-widened span fetched and cached)",
)

# metadata scale-out plane (ISSUE 15, see docs/perf.md "Metadata
# plane"): the prefix-sharded filer store's shape and churn, and the
# durable meta-log change feed's health
META_SHARD_OPS = REGISTRY.counter(
    "seaweedfs_tpu_meta_shard_ops_total",
    "filer-store operations routed through the prefix-sharded store, "
    "by op kind (find/find_many/list/insert/delete/delete_children)",
)
META_SHARD_COUNT = REGISTRY.gauge(
    "seaweedfs_tpu_meta_shard_count",
    "shards in the prefix-sharded filer store's committed shard map",
)
META_SHARD_REBALANCES = REGISTRY.counter(
    "seaweedfs_tpu_meta_shard_rebalances_total",
    "heat-driven shard-map rebalances committed (purge/copy/commit/"
    "cleanup moves of a directory band to the cooler neighbor)",
)
META_SHARD_MOVED = REGISTRY.counter(
    "seaweedfs_tpu_meta_shard_moved_entries_total",
    "filer entries copied between shards by rebalance moves",
)
META_FEED_EVENTS = REGISTRY.counter(
    "seaweedfs_tpu_meta_feed_events_total",
    "namespace change events appended to the durable meta-log "
    "change feed (segmented on-disk log)",
)
META_FEED_SEGMENTS = REGISTRY.gauge(
    "seaweedfs_tpu_meta_feed_segment_count",
    "on-disk segments currently retained by the durable meta log",
)
META_FEED_EVICTIONS = REGISTRY.counter(
    "seaweedfs_tpu_meta_feed_cache_evictions_total",
    "object-cache entries proactively evicted by change-feed events "
    "(overwrite/delete/rename seen before the next read, not by "
    "validate-on-hit)",
)

# metadata serving fleet (ISSUE 20, see docs/perf.md "Metadata fleet"):
# shard-range filer PROCESSES behind one crash-safe fleet map, the
# gate-batched write seam, and meta-log-fed read replicas
FLEET_FORWARDED = REGISTRY.counter(
    "seaweedfs_tpu_fleet_forwarded_total",
    "filer requests forwarded to the owning fleet member because the "
    "fleet map routes the path elsewhere, by op — zero-misroute never "
    "depends on client map freshness, the server-side hop is the "
    "authority",
)
FLEET_INGESTED = REGISTRY.counter(
    "seaweedfs_tpu_fleet_ingested_entries_total",
    "entries applied straight to the local store by FleetIngest "
    "(range-move copy/delta pages and directory-spine broadcasts)",
)
FLEET_MOVES = REGISTRY.counter(
    "seaweedfs_tpu_fleet_range_moves_total",
    "fleet range moves by outcome (committed/failed): a committed move "
    "re-homed a prefix range between two live filer processes under "
    "the fence-and-delta discipline",
)
META_WRITE_GATE_BATCHES = REGISTRY.counter(
    "seaweedfs_tpu_meta_write_gate_batches_total",
    "write-gate flushes: each one is ONE store round (insert_many) "
    "carrying every create/update enqueued in the same event-loop tick",
)
META_WRITE_GATE_WRITES = REGISTRY.counter(
    "seaweedfs_tpu_meta_write_gate_writes_total",
    "individual entry writes that rode a write-gate flush (writes / "
    "batches = the measured coalescing factor)",
)
FOLLOWER_EVENTS = REGISTRY.counter(
    "seaweedfs_tpu_meta_follower_events_total",
    "meta-log events a read replica applied to its local store, by "
    "type (upsert/delete/rename)",
)
FOLLOWER_REDIRECTS = REGISTRY.counter(
    "seaweedfs_tpu_meta_follower_redirects_total",
    "follower reads redirected to the primary because the caller's "
    "read-your-writes watermark (min_ts_ns) was ahead of the tail "
    "cursor",
)
ARENA_PREFETCH = REGISTRY.counter(
    "seaweedfs_tpu_arena_prefetch_total",
    "LSM flush-path arena residency hints, by result (queued = this "
    "hint scheduled the refresh, piggybacked = one was already queued, "
    "resident = already uploaded, no_arena = no device gate ever "
    "created an arena, unavailable = device absent or arena killed, "
    "error = hint path failed — never the flush itself)",
)
GEO_RESYNCS = REGISTRY.counter(
    "seaweedfs_tpu_geo_resyncs_total",
    "operator-driven geo full resyncs by outcome (ok/failed): a "
    "namespace re-seed from the primary after MetaLogTrimmed halted "
    "the tail",
)
GEO_RESYNCED_ENTRIES = REGISTRY.counter(
    "seaweedfs_tpu_geo_resynced_entries_total",
    "entries re-seeded onto the peer by geo full resyncs, by kind "
    "(upserted/pruned)",
)
GEO_TOMBSTONES = REGISTRY.counter(
    "seaweedfs_tpu_geo_tombstones_total",
    "geo tombstones written under /.seaweedfs/geo_tomb for replicated "
    "deletes/renames, by op (delete/rename) — the replay shield for "
    "destructive events whose target entry no longer exists",
)

# cold-tier follow-up (ISSUE 15 satellite): remote objects deleted by
# the master-dispatched orphan sweep — bytes leaked by crashes between
# manifest uncommit and remote delete, reclaimed (never data)
TIER_ORPHANS_SWEPT = REGISTRY.counter(
    "seaweedfs_tpu_tier_orphans_swept_total",
    "remote cold-tier objects deleted by the orphan sweep because no "
    "live .ctm manifest names them (past the grace age)",
)

# the registry seam the bounded-cardinality lint checks: every family
# that carries a `tenant` label MUST be listed here, or a retired
# tenant's series would survive the purge and grow cardinality without
# bound (tests/test_metrics_exposition.py pins this)
TENANT_LABELED_FAMILIES = (
    OVERLOAD_SHED,
    TENANT_QUEUE_DEPTH,
    TENANT_ADMITTED,
    TENANT_ADMITTED_SECONDS,
)
