"""Leveled V-style logging (ref: weed/glog/ — vendored glog fork).

Thin adapter over the stdlib: `V(2).info(...)` emits only when the global
verbosity is >= 2, matching the reference's glog.V(n).Infof convention.
"""

from __future__ import annotations

import logging
import os
import sys

_VERBOSITY = int(os.environ.get("SEAWEEDFS_TPU_V", "0"))

_logger = logging.getLogger("seaweedfs_tpu")
if not _logger.handlers:
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter("%(levelname).1s%(asctime)s %(name)s: %(message)s", "%m%d %H:%M:%S")
    )
    _logger.addHandler(handler)
    _logger.setLevel(logging.INFO)


def set_verbosity(v: int) -> None:
    global _VERBOSITY
    _VERBOSITY = v


class _VLogger:
    def __init__(self, enabled: bool):
        self.enabled = enabled

    def info(self, msg: str, *args) -> None:
        if self.enabled:
            _logger.info(msg, *args)


def V(level: int) -> _VLogger:  # noqa: N802 - glog convention
    return _VLogger(level <= _VERBOSITY)


def info(msg: str, *args) -> None:
    _logger.info(msg, *args)


def warning(msg: str, *args) -> None:
    _logger.warning(msg, *args)


def error(msg: str, *args) -> None:
    _logger.error(msg, *args)
