"""Security: HS256 JWT per-fid tokens + access guard.

Mirrors the reference's model (ref: weed/security/jwt.go:21-40,
guard.go:43-62): the master signs a short-lived token scoped to a file id at
assign time; volume servers verify it on writes when a signing key is
configured. Implemented with stdlib hmac (no external jwt dependency).
"""

from __future__ import annotations

import base64
import hmac
import json
import time
from dataclasses import dataclass
from hashlib import sha256


def _b64(data: bytes) -> bytes:
    return base64.urlsafe_b64encode(data).rstrip(b"=")


def _unb64(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(data + pad)


def gen_jwt(signing_key: str, expires_seconds: int, fid: str) -> str:
    """Signed token bound to one file id (ref jwt.go GenJwt)."""
    if not signing_key:
        return ""
    header = _b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    claims = {"Fid": fid}
    if expires_seconds:
        claims["exp"] = int(time.time()) + expires_seconds
    payload = _b64(json.dumps(claims).encode())
    msg = header + b"." + payload
    sig = _b64(hmac.new(signing_key.encode(), msg, sha256).digest())
    return (msg + b"." + sig).decode()


class TokenError(Exception):
    pass


def decode_jwt(signing_key: str, token: str) -> dict:
    """Verify signature + expiry; returns claims (ref jwt.go DecodeJwt)."""
    try:
        header, payload, sig = token.split(".")
    except ValueError as e:
        raise TokenError("malformed token") from e
    msg = f"{header}.{payload}".encode()
    expected = _b64(hmac.new(signing_key.encode(), msg, sha256).digest()).decode()
    if not hmac.compare_digest(sig, expected):
        raise TokenError("invalid signature")
    claims = json.loads(_unb64(payload))
    if "exp" in claims and time.time() > claims["exp"]:
        raise TokenError("token expired")
    return claims


def verify_fid_token(signing_key: str, token: str, fid: str) -> None:
    """Raise unless the token authorizes this exact fid (ref
    volume_server_handlers.go:90 requires sc.Fid == vid+","+fid; a
    volume-prefix match would let one upload token write every needle on
    the volume). An extension suffix on the requested fid is ignored."""
    claims = decode_jwt(signing_key, token)
    token_fid = claims.get("Fid", "")
    if token_fid == fid.split(".")[0]:
        return
    # canonicalize both sides so "_delta" chunk fids and the /vid/fid URL
    # form compare equal to the comma form the token was minted for —
    # still an exact (vid, key, cookie) match, never a volume-prefix one
    try:
        from ..storage.file_id import FileId

        if FileId.parse(token_fid) == FileId.parse(fid):
            return
    except ValueError:
        pass
    raise TokenError("token fid mismatch")


@dataclass
class Guard:
    """Whitelist + JWT gate for HTTP handlers (ref guard.go)."""

    white_list: tuple = ()
    signing_key: str = ""
    expires_seconds: int = 10

    @property
    def is_active(self) -> bool:
        return bool(self.white_list or self.signing_key)

    def _parsed_whitelist(self):
        """(exact_ips, networks) parsed once — check_whitelist runs on the
        hot write path."""
        key = tuple(self.white_list)
        cached = getattr(self, "_whitelist_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        import ipaddress

        exact = set()
        networks = []
        for entry in self.white_list:
            if "/" in entry:
                try:
                    networks.append(ipaddress.ip_network(entry, strict=False))
                except ValueError:
                    continue
            else:
                exact.add(entry)
        self._whitelist_cache = (key, (exact, networks))
        return exact, networks

    def check_whitelist(self, peer_ip: str) -> bool:
        """Exact IPs and CIDR networks (ref guard.go checkWhiteList)."""
        if not self.white_list:
            return True
        exact, networks = self._parsed_whitelist()
        if peer_ip in exact:
            return True
        if not networks:
            return False
        import ipaddress

        try:
            ip = ipaddress.ip_address(peer_ip)
        except ValueError:
            return False
        return any(ip in net for net in networks)

    def check_jwt(self, auth_header: str, fid: str) -> bool:
        if not self.signing_key:
            return True
        if not auth_header.startswith("Bearer "):
            return False
        try:
            verify_fid_token(self.signing_key, auth_header[7:], fid)
            return True
        except TokenError:
            return False


def real_remote(request) -> str:
    """The client address behind the fast-tier fallback proxy.

    The byte-level data-plane front (util/fasthttp.py) replays cold
    requests to the internal aiohttp listener over loopback, carrying the
    original peer in X-Forwarded-For. Trust that header ONLY when the
    direct peer is loopback (i.e. the proxy itself — anything local is
    already inside the trust boundary); a remote client's spoofed header
    is ignored.
    """
    remote = request.remote or ""
    if remote in ("127.0.0.1", "::1"):
        fwd = request.headers.get("X-Forwarded-For", "")
        if fwd:
            return fwd.split(",")[0].strip()
    return remote
