"""Out-of-process jax-backend health probe, shared by bench.py and
__graft_entry__.py.

The tunneled backend has two live-observed failure modes: raising at
transfer time, and HANGING forever in make_c_api_client when the relay is
down. An in-process probe cannot survive the hang, so the probe runs a
tiny device_put in a subprocess with a deadline.

GRAFT_PROBE_CMD overrides the probe's Python code — the hermetic
injection seam (tests force either verdict with e.g. "pass" /
"import sys; sys.exit(3)" instead of depending on live tunnel state).
"""

from __future__ import annotations

import os
import subprocess
import sys

DEFAULT_PROBE_CODE = (
    "import jax, numpy as np; "
    "jax.device_put(np.zeros(8, np.uint8)).block_until_ready()"
)


def probe_device_backend(timeout: float = 120.0) -> tuple[str, str]:
    """-> (verdict, detail). Verdict is explicitly three-state so no
    caller can truthiness-test a hang into "usable":

    - "ok":      healthy backend
    - "down":    probe failed fast (relay up, backend erroring)
    - "timeout": probe hung to its deadline = hard-down relay
    """
    probe_code = os.environ.get("GRAFT_PROBE_CMD", DEFAULT_PROBE_CODE)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", probe_code],
            capture_output=True,
            timeout=timeout,
        )
        if proc.returncode == 0:
            return "ok", ""
        return "down", (
            f"probe rc={proc.returncode}: "
            + proc.stderr.decode("utf-8", "replace")[-300:]
        )
    except subprocess.TimeoutExpired:
        return "timeout", f"probe HUNG >{timeout:.0f}s (dead relay/tunnel)"
    except Exception as e:  # pragma: no cover - subprocess machinery
        return "down", repr(e)
