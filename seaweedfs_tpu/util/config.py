"""TOML configuration with env overrides (ref: weed/util/config.go:19-51).

Search path mirrors the reference's viper setup: the working directory,
~/.seaweedfs-tpu, then /etc/seaweedfs-tpu — first hit wins per file name.
Values can be overridden from the environment with the same convention as
the reference's `WEED_` prefix: `WEED_<SECTION>_<KEY>` (dots become
underscores, case-insensitive), e.g. `WEED_MASTER_PORT=9444` overrides
`[master] port`.

Files are produced by `weed-tpu scaffold` and consumed by the server
commands via their -config flag.
"""

from __future__ import annotations

import os

try:
    import tomllib  # Python >= 3.11
except ImportError:  # pragma: no cover - environment-dependent
    import tomli as tomllib  # same API, the backport package
from typing import Any, Optional

SEARCH_PATHS = [".", os.path.expanduser("~/.seaweedfs-tpu"), "/etc/seaweedfs-tpu"]
ENV_PREFIX = "WEED_"


class Configuration:
    """Parsed TOML + env-override lookup."""

    def __init__(self, data: dict, source: str = ""):
        self.data = data
        self.source = source

    def get(self, dotted_key: str, default: Any = None) -> Any:
        """`section.key` lookup; `WEED_SECTION_KEY` env vars win
        (ref GetViper's AutomaticEnv + SetEnvPrefix, config.go:44-51).
        Env strings are coerced to the type of the file/default value."""
        env_name = ENV_PREFIX + dotted_key.upper().replace(".", "_")
        node: Any = self.data
        for part in dotted_key.split("."):
            if not isinstance(node, dict) or part not in node:
                node = None
                break
            node = node[part]
        if env_name in os.environ:
            raw = os.environ[env_name]
            model = node if node is not None else default
            return _coerce(raw, model)
        return node if node is not None else default

    def section(self, name: str) -> dict:
        """A whole section with env overrides applied per key."""
        base = dict(self.data.get(name, {}))
        prefix = ENV_PREFIX + name.upper() + "_"
        for env_name, raw in os.environ.items():
            if env_name.startswith(prefix):
                key = env_name[len(prefix) :].lower()
                # match an existing key case-insensitively (flag-style keys
                # like volumeSizeLimitMB live lowercase in the env name)
                target = next(
                    (k for k in base if k.lower() == key), key
                )
                base[target] = _coerce(raw, base.get(target))
        return base


def _coerce(raw: str, model: Any) -> Any:
    if isinstance(model, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(model, int):
        try:
            return int(raw)
        except ValueError:
            return raw
    if isinstance(model, float):
        try:
            return float(raw)
        except ValueError:
            return raw
    return raw


def load_configuration(
    name_or_path: str,
    required: bool = False,
    search_paths: Optional[list[str]] = None,
) -> Optional[Configuration]:
    """Load `<name>.toml` from the search path, or an explicit file path
    (ref LoadConfiguration, config.go:19-42)."""
    candidates = []
    if name_or_path.endswith(".toml") or "/" in name_or_path:
        candidates.append(name_or_path)
    else:
        for d in search_paths or SEARCH_PATHS:
            candidates.append(os.path.join(d, name_or_path + ".toml"))
    for path in candidates:
        if os.path.exists(path):
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f), source=path)
    if required:
        raise FileNotFoundError(
            f"no {name_or_path}.toml found in {search_paths or SEARCH_PATHS}; "
            "generate one with `weed-tpu scaffold -output .`"
        )
    return None
