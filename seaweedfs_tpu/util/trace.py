"""Distributed tracing plane: cross-hop spans, tail-based sampling and an
always-on flight recorder (ISSUE 8 tentpole).

Every serving plane already exposes per-stage histograms, but those are
aggregates: when the open-loop p999 spikes, nothing connects one slow S3
PUT to the specific master lease, gate batch, volume append and replica
fan-out it rode. This module closes that attribution gap:

- **Context**: a W3C-traceparent-style (trace_id, span_id, sampled) triple
  carried through `contextvars`, so one request's identity follows it
  across awaits, `ensure_future` fan-outs and `call_soon` continuations.
  Propagation over HTTP rides a ``traceparent`` header
  (`util/fasthttp.py` client inject, `server/serving_core.py` server
  extract — byte-level parse, no regex) and over the gRPC seam via call
  metadata (`pb/rpc.py`), so master/volume/filer/S3 all join one trace.

- **Flight recorder**: finished spans land in a bounded per-process ring
  (`SEAWEEDFS_TPU_TRACE_RING` spans, default 4096) — always on, never
  growing, exported as JSONL at ``/debug/traces`` on every server and
  merged cluster-wide by the ``trace.dump`` shell command.

- **Tail-based sampling**: a configurable head fraction
  (`SEAWEEDFS_TPU_TRACE_SAMPLE`, default 0.01) is recorded up front, but
  the slow and weird requests are kept BY CONSTRUCTION even at sample=0:
  roots that exceed the live p99 (tracked in an allocation-free log
  histogram over every root request) are retro-promoted, and requests
  that touched an error / retry / hedge / injected fault are flagged on
  their context and promoted at finish. The unsampled fast path allocates
  NOTHING per request — no context object, no span — which the
  `serving.trace_overhead` bench leg asserts via the admission counters
  (ring admissions == spans of sampled+promoted requests, never one per
  request).

- **Span links**: batch seams (lookup gate, chunk-upload gate, group
  commit) amortize many requests into one flush; the flush records ONE
  span that adopts the first sampled member's trace and carries
  ``links`` to every member (trace_id, span_id), so per-request timelines
  show the shared work they rode.

- **Background planes**: scrub/vacuum/repair/anti-entropy open root spans
  tagged ``plane=...`` (`span_root`), and their dispatch RPCs inherit the
  context — serving-vs-maintenance interference is visible in one
  timeline.

The reference (weed/) has no tracing; the design follows the W3C Trace
Context wire format and Dapper-style in-process recording.
"""

from __future__ import annotations

import contextvars
import json
import os
import random
import threading
import time
from typing import Optional

from . import metrics as _metrics

# ---------------------------------------------------------------- context --

FLAG_ERROR = 1
FLAG_RETRY = 2
FLAG_HEDGE = 4
FLAG_FAULT = 8
FLAG_SHED = 16

_FLAG_NAMES = (
    (FLAG_ERROR, "error"),
    (FLAG_RETRY, "retry"),
    (FLAG_HEDGE, "hedge"),
    (FLAG_FAULT, "fault"),
    (FLAG_SHED, "shed"),
)


class SpanCtx:
    """One hop's identity: 128-bit trace id, 64-bit span id, sampled flag,
    plus the tail-sampling flags accumulated while the request ran."""

    __slots__ = ("trace_id", "span_id", "sampled", "flags")

    def __init__(self, trace_id: int, span_id: int, sampled: bool):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.flags = 0


_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "seaweedfs_tpu_trace", default=None
)

_rand = random.Random()


def _new_span_id() -> int:
    return _rand.getrandbits(64) or 1


def _new_trace_id() -> int:
    return _rand.getrandbits(128) or 1


def current() -> Optional[SpanCtx]:
    return _CTX.get()


def current_sampled() -> Optional[SpanCtx]:
    c = _CTX.get()
    return c if c is not None and c.sampled else None


def current_trace_hex() -> Optional[str]:
    """Hex trace id of the current SAMPLED context (metrics exemplars)."""
    c = _CTX.get()
    if c is None or not c.sampled:
        return None
    return "%032x" % c.trace_id


def flag(bit: int) -> None:
    """Mark the current trace as having touched an error/retry/hedge/fault
    — a no-op without a context (the zero-alloc unsampled path stays
    zero-alloc), a promotion trigger for unsampled-but-propagated ones."""
    c = _CTX.get()
    if c is not None:
        c.flags |= bit


# ------------------------------------------------------------- wire format --


def format_traceparent(ctx: SpanCtx) -> str:
    return "00-%032x-%016x-%s" % (
        ctx.trace_id, ctx.span_id, "01" if ctx.sampled else "00"
    )


def format_traceparent_bytes(ctx: SpanCtx) -> bytes:
    return format_traceparent(ctx).encode("ascii")


def parse_traceparent(raw) -> Optional[SpanCtx]:
    """Byte-level fast parse of a ``traceparent`` value ->
    SpanCtx(parent ids) or None on any malformation. Accepts str too
    (gRPC metadata values arrive as str)."""
    if raw is None:
        return None
    if isinstance(raw, str):
        raw = raw.encode("ascii", "replace")
    if len(raw) < 55:
        return None
    # 00-<32 hex>-<16 hex>-<2 hex>
    if raw[2] != 0x2D or raw[35] != 0x2D or raw[52] != 0x2D:
        return None
    try:
        trace_id = int(raw[3:35], 16)
        span_id = int(raw[36:52], 16)
        flags = int(raw[53:55], 16)
    except ValueError:
        return None
    if not trace_id or not span_id:
        return None
    return SpanCtx(trace_id, span_id, bool(flags & 1))


# ------------------------------------------------------------ the recorder --


def _env_float(name: str, default: str) -> float:
    try:
        return float(os.environ.get(name, default) or 0.0)
    except ValueError:
        return float(default)


class Recorder:
    """Per-process flight recorder: bounded span ring + sampling state.

    The ring only ever receives spans of sampled (head or promoted)
    traces; `admitted` counts ring writes and the per-reason counters
    partition where sampling decisions came from, so
    ``admitted == spans created for sampled traces`` is checkable from
    the outside (the no-per-request-allocation assertion)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.configure(
            enabled=(os.environ.get("SEAWEEDFS_TPU_TRACE", "1") or "1") != "0",
            sample=_env_float("SEAWEEDFS_TPU_TRACE_SAMPLE", "0.01"),
            capacity=int(
                _env_float("SEAWEEDFS_TPU_TRACE_RING", "4096") or 4096
            ),
        )

    def configure(
        self,
        enabled: Optional[bool] = None,
        sample: Optional[float] = None,
        capacity: Optional[int] = None,
        min_roots: int = 500,
    ) -> None:
        """(Re)configure and reset counters/ring — tests and the
        trace_overhead bench flip enabled/sample between phases."""
        with self._lock:
            if enabled is not None:
                self.enabled = enabled
            if sample is not None:
                self.sample = max(0.0, min(1.0, sample))
            if capacity is not None:
                self.capacity = max(16, capacity)
            self._ring: list = [None] * self.capacity
            self._n = 0
            self.admitted = 0
            self.dropped = 0
            self.sampled_roots = 0
            self.joined = 0
            self.promoted_slow = 0
            self.promoted_flagged = 0
            self.promoted_fault = 0
            # allocation-free root-latency log histogram (2x-wide
            # ns-bit-length buckets): feeds the live-p99 promotion
            # threshold for roots the head sampler skipped
            self._root_buckets = [0] * 64
            self._root_count = 0
            self._slow_ns = float("inf")
            # same threshold in SECONDS as a plain attribute, so the
            # serving-core hot path can do one float compare instead of
            # an is_slow() method call per request
            self.slow_s = float("inf")
            self.min_roots = min_roots

    reset = configure  # alias: tests call RECORDER.reset()

    # --- sampling ---
    def head_sample(self) -> bool:
        return self.sample > 0.0 and _rand.random() < self.sample

    def note_root(self, dt_seconds: float) -> None:
        """Record one root request's wall into the p99 tracker — int ops
        only, no allocation (runs on EVERY request when tracing is
        enabled, sampled or not)."""
        ns = int(dt_seconds * 1e9)
        b = ns.bit_length()
        if b > 63:
            b = 63
        self._root_buckets[b] += 1
        self._root_count += 1
        if self._root_count & 0xFF == 0:
            self._recompute_slow()

    def _recompute_slow(self) -> None:
        total = self._root_count
        if total < self.min_roots:
            return
        target = total * 0.99
        acc = 0
        for i, c in enumerate(self._root_buckets):
            acc += c
            if acc >= target:
                # promote only past the bucket's UPPER edge (bucket i
                # holds bit_length==i, i.e. [2^(i-1), 2^i)): the gate
                # lands between p99 and 2*p99 of observed roots, so
                # promotions stay a sub-1% tail, never a steady stream
                self._slow_ns = float(1 << i)
                self.slow_s = self._slow_ns / 1e9
                return

    def is_slow(self, dt_seconds: float) -> bool:
        return dt_seconds * 1e9 > self._slow_ns

    # --- recording ---
    def record(self, span: dict) -> None:
        with self._lock:
            i = self._n % self.capacity
            if self._ring[i] is not None:
                self.dropped += 1
            self._ring[i] = span
            self._n += 1
            self.admitted += 1

    def promote_slow(self, name: str, dt: float, **tags) -> None:
        """Retro-record a root span for an untraced request that finished
        past the live p99 — the tail kept by construction."""
        self.promoted_slow += 1
        ctx = SpanCtx(_new_trace_id(), _new_span_id(), True)
        self.record(
            _span_dict(
                ctx, 0, name, time.time() - dt, dt,
                dict(tags, promoted="slow"), None, None,
            )
        )

    def promote_fault(self, name: str, kind: str, **tags) -> None:
        """Retro-record a root span for an untraced request that hit the
        fault-injection seam (promotion even at sample=0)."""
        self.promoted_fault += 1
        ctx = SpanCtx(_new_trace_id(), _new_span_id(), True)
        self.record(
            _span_dict(
                ctx, 0, name, time.time(), 0.0,
                dict(tags, promoted="fault", fault=kind), None, None,
            )
        )

    # --- export ---
    def spans(self) -> list:
        """Ring contents, oldest first."""
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                return [s for s in self._ring[:n] if s is not None]
            i = n % cap
            return [s for s in self._ring[i:] + self._ring[:i] if s is not None]

    def dump_jsonl(self) -> str:
        return "".join(json.dumps(s) + "\n" for s in self.spans())

    def status(self) -> dict:
        return {
            "enabled": self.enabled,
            "sample": self.sample,
            "capacity": self.capacity,
            "spans_in_ring": min(self._n, self.capacity),
            "admitted": self.admitted,
            "dropped": self.dropped,
            "sampled_roots": self.sampled_roots,
            "joined": self.joined,
            "promoted_slow": self.promoted_slow,
            "promoted_flagged": self.promoted_flagged,
            "promoted_fault": self.promoted_fault,
            "roots_seen": self._root_count,
            "slow_threshold_ms": (
                round(self._slow_ns / 1e6, 3)
                if self._slow_ns != float("inf")
                else None
            ),
        }


RECORDER = Recorder()


def _span_dict(
    ctx: SpanCtx,
    parent_id: int,
    name: str,
    start: float,
    dur: float,
    tags: Optional[dict],
    links,
    err: Optional[str],
) -> dict:
    d = {
        "trace": "%032x" % ctx.trace_id,
        "span": "%016x" % ctx.span_id,
        "parent": ("%016x" % parent_id) if parent_id else None,
        "name": name,
        "start": round(start, 6),
        "dur_us": round(dur * 1e6, 1),
    }
    if tags:
        d["tags"] = tags
    if links:
        d["links"] = [
            {"trace": "%032x" % t, "span": "%016x" % s} for t, s in links
        ]
    if err:
        d["err"] = err
    if ctx.flags:
        d["flags"] = [n for b, n in _FLAG_NAMES if ctx.flags & b]
    return d


# ---------------------------------------------------------------- spans --


class ActiveSpan:
    """A request-scoped span: installs its context on construction,
    records (when sampled, or promoted via flags) and restores the outer
    context on finish(). Built by `begin_request`."""

    __slots__ = ("name", "ctx", "parent_id", "tags", "start", "_t0", "_token")

    def __init__(self, name: str, ctx: SpanCtx, parent_id: int, tags: dict):
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.tags = tags
        self.start = time.time()
        self._t0 = time.perf_counter()
        self._token = _CTX.set(ctx)

    def tag(self, key: str, value) -> None:
        self.tags[key] = value

    def drop(self) -> None:
        """Restore the outer context WITHOUT recording — for requests
        that turn out to be proxied (FALLBACK): the µs fast-tier
        hand-off wall is not the request, and a head-sampled root here
        would be an orphan (the replay carries the client's original
        headers, not this span's identity)."""
        try:
            _CTX.reset(self._token)
        except ValueError:
            pass

    def finish(self, err: Optional[BaseException] = None) -> float:
        try:
            _CTX.reset(self._token)
        except ValueError:
            pass  # finished from a different context (detached completion)
        ctx = self.ctx
        dur = time.perf_counter() - self._t0
        if err is not None:
            ctx.flags |= FLAG_ERROR
        rec = RECORDER
        if ctx.sampled:
            rec.record(
                _span_dict(
                    ctx, self.parent_id, self.name, self.start, dur,
                    self.tags, None, str(err) if err else None,
                )
            )
        elif ctx.flags:
            # tail promotion: an unsampled-but-propagated request touched
            # an error/retry/hedge/fault — keep it
            ctx.sampled = True
            rec.promoted_flagged += 1
            rec.record(
                _span_dict(
                    ctx, self.parent_id, self.name, self.start, dur,
                    dict(self.tags, promoted="flagged"), None,
                    str(err) if err else None,
                )
            )
        return dur


def begin_request(
    name: str, parent: Optional[SpanCtx] = None, **tags
) -> Optional[ActiveSpan]:
    """Server-side entry point (HTTP fast tier, gRPC handlers, aiohttp
    middleware). Joins `parent` when given (sampled or not — unsampled
    joins still carry flags for tail promotion); with parent=None the
    CALLER has already won the head-sample coin (`RECORDER.head_sample`)
    and this starts a sampled root. The untraced fast path therefore
    never reaches this function — the coin is two comparisons and no
    allocation at the call site."""
    rec = RECORDER
    if not rec.enabled:
        return None
    if parent is not None:
        ctx = SpanCtx(parent.trace_id, _new_span_id(), parent.sampled)
        rec.joined += 1
        return ActiveSpan(name, ctx, parent.span_id, tags)
    rec.sampled_roots += 1
    ctx = SpanCtx(_new_trace_id(), _new_span_id(), True)
    return ActiveSpan(name, ctx, 0, tags)


class _NullSpan:
    """Shared no-op context manager for the unsampled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def tag(self, key, value) -> None:
        pass

    def link(self, ctx) -> None:
        pass


_NULL = _NullSpan()
NULL_SPAN = _NULL  # public no-op CM for conditional span sites


class _SpanCM:
    """Child-span context manager (``with trace.span("filer.lease"):``).
    Only built when the current context is sampled; installs a child
    context for the duration so downstream hops parent correctly."""

    __slots__ = ("name", "ctx", "parent_id", "tags", "links", "start",
                 "_t0", "_token")

    def __init__(self, name: str, ctx: SpanCtx, parent_id: int, tags: dict):
        self.name = name
        self.ctx = ctx
        self.parent_id = parent_id
        self.tags = tags
        self.links: Optional[list] = None

    def tag(self, key: str, value) -> None:
        self.tags[key] = value

    def link(self, ctx: SpanCtx) -> None:
        if self.links is None:
            self.links = []
        self.links.append((ctx.trace_id, ctx.span_id))

    def __enter__(self):
        self.start = time.time()
        self._t0 = time.perf_counter()
        self._token = _CTX.set(self.ctx)
        return self

    def __exit__(self, et, ev, tb):
        try:
            _CTX.reset(self._token)
        except ValueError:
            pass
        RECORDER.record(
            _span_dict(
                self.ctx, self.parent_id, self.name, self.start,
                time.perf_counter() - self._t0, self.tags, self.links,
                str(ev) if ev is not None else None,
            )
        )
        return False


def span(name: str, **tags):
    """In-process child span of the current context. Returns a shared
    no-op when untraced/unsampled — safe on hot paths."""
    c = _CTX.get()
    if c is None or not c.sampled or not RECORDER.enabled:
        return _NULL
    child = SpanCtx(c.trace_id, _new_span_id(), True)
    return _SpanCM(name, child, c.span_id, tags)


def span_root(name: str, **tags):
    """Always-recorded root span for background planes (scrub, vacuum,
    repair, anti-entropy): tag ``plane=...`` so maintenance work shows up
    in the same timeline as the serving traces it interferes with.
    Dispatch RPCs made inside inherit the context."""
    if not RECORDER.enabled:
        return _NULL
    ctx = SpanCtx(_new_trace_id(), _new_span_id(), True)
    return _SpanCM(name, ctx, 0, tags)


def batch_span(name: str, members: list, **tags):
    """Flush span for a batch seam (lookup gate / chunk-upload gate /
    group commit): adopts the FIRST sampled member's trace (so merging by
    trace_id finds it) and links every member context, making the
    amortized work visible from each rider's timeline. `members` is the
    list of sampled member SpanCtx objects captured at enqueue; no-op
    when none were sampled."""
    if not members or not RECORDER.enabled:
        return _NULL
    first = members[0]
    ctx = SpanCtx(first.trace_id, _new_span_id(), True)
    cm = _SpanCM(name, ctx, first.span_id, dict(tags, members=len(members)))
    for m in members:
        cm.link(m)
    return cm


def note_fault(name: str, kind: str, **tags) -> None:
    """Fault-seam hook: flag the current trace, or — when the request is
    untraced (sample=0, no upstream header) — retro-promote a root span
    so injected faults are ALWAYS kept (the e2e acceptance invariant)."""
    rec = RECORDER
    if not rec.enabled:
        return
    c = _CTX.get()
    if c is not None:
        c.flags |= FLAG_FAULT
        return
    rec.promote_fault(name, kind, **tags)


def note_shed(name: str, **tags) -> None:
    """Admission-gate hook: a shed request flags its trace (joined from
    the caller's traceparent) or retro-promotes a root — load-shedding
    decisions are kept by the tail sampler even at sample=0, exactly
    like injected faults. No-op (one attr load) while the recorder is
    off, so the µs shed path stays µs."""
    rec = RECORDER
    if not rec.enabled:
        return
    c = _CTX.get()
    if c is not None:
        c.flags |= FLAG_SHED
        return
    rec.promote_fault(name, "shed", **tags)


# exemplar hook: histograms ask for the live sampled trace id at observe
# time (metrics.py must not import trace — this wiring keeps the
# dependency one-way)
_metrics._exemplar_fn = current_trace_hex
