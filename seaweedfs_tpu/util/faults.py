"""Deterministic, seed-driven fault injection for the serving paths.

A `FaultPlan` is a schedule of `FaultRule`s keyed by (operation, target):
"the 3rd write_at on volume 7's .dat fails with EIO", "10% of
VolumeEcShardRead RPCs to host B see 200ms latency then a reset". The plan
is consulted from three seams:

- the backend-storage file interface (`storage/backend.py`): short/torn
  writes, mid-write crash, EIO, fsync failure, read latency;
- the dynamic-gRPC client (`pb/rpc.py` Stub.call/server_stream):
  connection reset, latency, hang-until-deadline;
- the HTTP data-plane client (`util/fasthttp.py` FastHTTPClient.request):
  connection reset, latency, synthesized 5xx.

Every probabilistic decision draws from a per-rule `random.Random` seeded
from (plan seed, rule index, rule key), so a plan replays identically for a
given seed and operation sequence regardless of unrelated interleaving.

Activation: `install_plan()` programmatically, or the environment variable
`SEAWEEDFS_TPU_FAULTS` naming a JSON plan file (or carrying inline JSON)
read once at import. With neither, `_PLAN` stays None and every seam is a
single module-attribute load plus an `is None` check — tier-1 runs
unchanged.

Crash semantics: a rule with fault="crash" performs a torn write (a prefix
of the payload) and then marks the plan dead; every later faultable
operation raises `SimulatedCrash`, like syscalls in a killed process. In
particular the write path's truncate-rollback cannot run, so the torn tail
stays on disk for `storage/volume.py`'s load-time recovery to find —
exactly the state a real `kill -9` mid-append leaves. Tests clear or swap
the plan before "restarting" the process (reloading the volume).
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import errno
import json
import os
import threading
import time
from dataclasses import dataclass
from fnmatch import fnmatchcase
from random import Random
from typing import Optional

from .metrics import FAULTS_INJECTED

# the address of the node MAKING the current outbound call, when known —
# pairwise `partition` rules need both endpoints, but the client seams
# only see the callee. In-process callers that have an identity (raft
# peers, server-to-server replication) wrap their calls in
# `calling_from(self.address)`; external/anonymous callers leave it None
# and only match a partition side whose pattern is "*".
_CALL_SOURCE: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "faults_call_source", default=None
)


@contextlib.contextmanager
def calling_from(address: str):
    """Tag outbound calls in this (async) context with the caller's own
    address, so pairwise partition rules can match both endpoints."""
    tok = _CALL_SOURCE.set(address)
    try:
        yield
    finally:
        _CALL_SOURCE.reset(tok)


def _source_matches(src: Optional[str], pattern: str) -> bool:
    if pattern == "*":
        return True
    if src is None:
        return False  # anonymous caller: only the wildcard side matches
    return fnmatchcase(src, pattern)


class SimulatedCrash(BaseException):
    """The process 'died' mid-operation. Derives from BaseException so
    per-operation `except Exception` cleanup handlers (e.g. the volume
    write path's truncate-rollback) cannot swallow it and tidy up state a
    real crash would have left torn."""


class InjectedError(OSError):
    """Marker base for injected I/O errors (still an OSError, so existing
    error handling treats it like the real thing)."""


def injected_eio(target: str) -> InjectedError:
    return InjectedError(errno.EIO, f"injected EIO on {target}")


@dataclass
class FaultRule:
    """One scheduled fault.

    op/target are fnmatch patterns: op names the seam ("write_at",
    "read_at", "sync", "truncate", "rpc:<Method>", "http:<METHOD>"), target
    the file path or host:port. Trigger is either `nth` (fire on the nth
    matching call, 1-based) or `probability` (per-match coin flip from the
    rule's seeded RNG); `times` caps total fires (default 1 for nth rules,
    unlimited for probability rules).
    """

    op: str
    target: str = "*"
    # eio|torn|crash|latency|reset|hang|http_error|bitflip|partition
    fault: str = "eio"
    nth: Optional[int] = None
    probability: Optional[float] = None
    times: Optional[int] = None
    delay: float = 0.0  # seconds, for latency/hang (hang: until deadline)
    keep: Optional[int] = None  # bytes written before a torn/crash write
    at_offset: Optional[int] = None  # absolute file offset (crash cut point,
    # or the byte a bitflip corrupts)
    status: int = 503  # synthesized status for http_error
    bits: int = 1  # bits flipped by a bitflip fault
    # time-windowed rules (brownouts): active only while the plan clock is
    # in [from_s, until_s) seconds since install; ramp=True scales a
    # latency rule's delay triangularly over the window (0 at the edges,
    # `delay` at the midpoint — degrade in, peak, recover)
    from_s: Optional[float] = None
    until_s: Optional[float] = None
    ramp: bool = False
    # partition rules: the far end of the cut. The rule fires when the
    # call's (source, target) pair matches (target, peer) in EITHER
    # orientation — traffic is dropped both directions. peer="*" (the
    # default) isolates `target` from everyone, including anonymous
    # callers; a concrete pattern makes the cut pairwise and only
    # matches callers that tagged themselves via `calling_from`.
    peer: Optional[str] = None

    def max_fires(self) -> Optional[int]:
        if self.times is not None:
            return self.times
        return 1 if self.nth is not None else None

    def window_factor(self, t: float) -> Optional[float]:
        """Delay scale at plan-relative time t: None when the rule is
        outside its window (inactive), 1.0 for unwindowed/unramped rules,
        else the triangular ramp position."""
        if self.from_s is None and self.until_s is None:
            return 1.0
        lo = self.from_s or 0.0
        hi = self.until_s if self.until_s is not None else float("inf")
        if not lo <= t < hi:
            return None
        if not self.ramp or hi == float("inf"):
            return 1.0
        mid = (t - lo) / (hi - lo)  # 0..1 across the window
        return 1.0 - abs(2.0 * mid - 1.0)


def brownout(
    op: str = "http:*",
    target: str = "*",
    delay: float = 0.2,
    start: float = 0.0,
    duration: float = 5.0,
    probability: float = 1.0,
) -> FaultRule:
    """Convenience constructor for a brownout: a ramped latency rule over
    a time window. For `duration` seconds beginning `start` seconds after
    the plan is installed, matching operations see injected latency that
    ramps 0 → `delay` → 0 triangularly across the window — the shape of a
    peer degrading (GC storm, thermal throttle, noisy neighbour) and
    recovering, as opposed to the step function a bare latency rule
    injects. Load harnesses and chaos tests were hand-rolling latency
    schedules for this; see docs/robustness.md's fault matrix."""
    return FaultRule(
        op=op,
        target=target,
        fault="latency",
        probability=probability,
        delay=delay,
        from_s=start,
        until_s=start + duration,
        ramp=True,
    )


def partition(
    a: str,
    b: str = "*",
    op: str = "*:*",
    start: float = 0.0,
    duration: Optional[float] = None,
) -> FaultRule:
    """Convenience constructor for a network partition: drop traffic both
    directions between two addresses, windowed like `brownout`. For
    `duration` seconds beginning `start` seconds after the plan is
    installed (forever when duration is None — heal by swapping the
    plan), every matching RPC/HTTP call whose (source, target) pair hits
    (a, b) in either orientation raises ConnectionError at the seam —
    the connection-refused shape of a firewalled peer, not a slow one.
    With b="*" (default) node `a` is isolated from the whole cluster;
    with a concrete `b` the cut is pairwise, and only callers that tag
    their outbound calls via `calling_from(addr)` (raft peers do) can
    match the source side. op="*:*" matches the RPC and HTTP client
    seams but no disk ops. See docs/robustness.md's fault matrix."""
    return FaultRule(
        op=op,
        target=a,
        peer=b,
        fault="partition",
        probability=1.0,
        from_s=start if (start or duration is not None) else None,
        until_s=(start + duration) if duration is not None else None,
    )


@dataclass
class FaultEvent:
    """One fired fault, as handed to a seam (and logged on the plan)."""

    rule: FaultRule
    op: str
    target: str
    rng: Random  # rule-scoped; seams draw torn-write cut points from it
    delay: float = 0.0  # effective delay for latency/hang rules: the
    # rule's delay scaled by its window ramp at fire time

    @property
    def kind(self) -> str:
        return self.rule.fault


class FaultPlan:
    def __init__(self, seed: int = 0, rules: Optional[list[FaultRule]] = None):
        self.seed = seed
        self.rules: list[FaultRule] = []
        self._lock = threading.Lock()
        self._match_counts: list[int] = []
        self._fire_counts: list[int] = []
        self._rngs: list[Random] = []
        self._dead = False
        # windowed rules (brownouts) measure time from this epoch;
        # install_plan restarts it so windows are install-relative
        self.epoch = time.monotonic()
        self.events: list[tuple[str, str, str]] = []  # (op, target, kind)
        for r in rules or []:
            self.add(r)

    def restart_clock(self) -> None:
        self.epoch = time.monotonic()

    def add(self, rule: FaultRule) -> "FaultPlan":
        with self._lock:
            i = len(self.rules)
            self.rules.append(rule)
            self._match_counts.append(0)
            self._fire_counts.append(0)
            # rule-scoped stream: firing decisions for one rule are
            # independent of how other rules' matches interleave
            self._rngs.append(Random(f"{self.seed}:{i}:{rule.op}:{rule.target}"))
        return self

    def mark_dead(self) -> None:
        with self._lock:
            self._dead = True

    @property
    def dead(self) -> bool:
        return self._dead

    def fired(self, op_pattern: str = "*") -> int:
        with self._lock:
            return sum(1 for op, _t, _k in self.events if fnmatchcase(op, op_pattern))

    def match(self, op: str, target: str) -> Optional[FaultEvent]:
        """Consult the schedule for one operation; returns the fault to
        apply, or None. Raises SimulatedCrash once the plan is dead."""
        with self._lock:
            if self._dead:
                raise SimulatedCrash(f"{op} on {target} after simulated crash")
            now_rel = time.monotonic() - self.epoch
            src = _CALL_SOURCE.get()
            for i, rule in enumerate(self.rules):
                if not fnmatchcase(op, rule.op):
                    continue
                if rule.fault == "partition":
                    # both directions: (src -> target) matches the cut
                    # (a, b) in either orientation
                    a, b = rule.target, rule.peer or "*"
                    if not (
                        (fnmatchcase(target, a) and _source_matches(src, b))
                        or (
                            fnmatchcase(target, b)
                            and _source_matches(src, a)
                        )
                    ):
                        continue
                elif not fnmatchcase(target, rule.target):
                    continue
                # windowed rules outside their window neither count a
                # match (nth bookkeeping) nor fire
                factor = rule.window_factor(now_rel)
                if factor is None:
                    continue
                self._match_counts[i] += 1
                cap = rule.max_fires()
                if cap is not None and self._fire_counts[i] >= cap:
                    continue
                fire = False
                if rule.nth is not None:
                    fire = self._match_counts[i] == rule.nth
                elif rule.probability is not None:
                    fire = self._rngs[i].random() < rule.probability
                else:
                    fire = True
                if not fire:
                    continue
                self._fire_counts[i] += 1
                self.events.append((op, target, rule.fault))
                FAULTS_INJECTED.inc(op=op.split(":")[0], kind=rule.fault)
                return FaultEvent(
                    rule=rule, op=op, target=target, rng=self._rngs[i],
                    delay=rule.delay * factor,
                )
        return None

    # --- (de)serialization: env-var / JSON-file activation ---
    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        plan = cls(seed=int(d.get("seed", 0)))
        for rd in d.get("rules", []):
            plan.add(FaultRule(**rd))
        return plan

    def to_dict(self) -> dict:
        out = {"seed": self.seed, "rules": []}
        for r in self.rules:
            rd = {"op": r.op, "target": r.target, "fault": r.fault}
            for k in ("nth", "probability", "times", "keep", "at_offset",
                      "from_s", "until_s", "peer"):
                v = getattr(r, k)
                if v is not None:
                    rd[k] = v
            if r.delay:
                rd["delay"] = r.delay
            if r.ramp:
                rd["ramp"] = True
            if r.fault == "http_error":
                rd["status"] = r.status
            if r.fault == "bitflip" and r.bits != 1:
                rd["bits"] = r.bits
            out["rules"].append(rd)
        return out


# process-global plan; seams read the module attribute directly so the
# disabled path costs one load + is-None test
_PLAN: Optional[FaultPlan] = None


def install_plan(plan: Optional[FaultPlan]) -> None:
    global _PLAN
    if plan is not None:
        # windowed rules (brownouts) run install-relative: a plan built
        # ahead of time must not have burned its window before activation
        plan.restart_clock()
    _PLAN = plan


def clear_plan() -> None:
    install_plan(None)


def current_plan() -> Optional[FaultPlan]:
    return _PLAN


def _load_env_plan() -> None:
    spec = os.environ.get("SEAWEEDFS_TPU_FAULTS", "")
    if not spec:
        return
    try:
        if spec.lstrip().startswith("{"):
            data = json.loads(spec)
        else:
            with open(spec) as f:
                data = json.load(f)
        install_plan(FaultPlan.from_dict(data))
    except Exception as e:  # a broken plan must be loud, not silently off
        raise ValueError(f"SEAWEEDFS_TPU_FAULTS unparseable: {e}") from e


_load_env_plan()


# ---------------------------------------------------------------- seams --


def sync_fault(
    plan: FaultPlan, op: str, target: str, allow_partial: bool = False,
    corruptable: bool = False,
) -> Optional[FaultEvent]:
    """Blocking-code seam (disk I/O): applies latency/EIO in place. With
    allow_partial (the write seam), torn/crash events are RETURNED for the
    caller to apply as a partial write; with corruptable (the read/write
    data seams), bitflip events are RETURNED for the caller to apply to
    the buffer via apply_bitflip. On every other seam a fired event must
    never be a counted no-op, so crash kills the plan here and torn /
    bitflip degrade to EIO."""
    ev = plan.match(op, target)
    if ev is None:
        return None
    kind = ev.kind
    if kind == "latency":
        time.sleep(ev.delay)
        return None
    if kind in ("eio", "fsync_fail"):
        raise injected_eio(target)
    if kind == "bitflip":
        if corruptable:
            return ev
        raise injected_eio(target)
    if kind == "partition":
        # a counted fault is never a no-op: on a disk seam the nearest
        # honest shape is an I/O error (network partitions target the
        # RPC/HTTP seams; op="*:*" cannot even match disk ops)
        raise injected_eio(target)
    if not allow_partial:
        if kind == "crash":
            plan.mark_dead()
            raise SimulatedCrash(f"crash in {op} of {target}")
        raise injected_eio(target)
    return ev


def apply_bitflip(ev: FaultEvent, data, file_offset: int = 0) -> bytes:
    """Silent data corruption: flip `rule.bits` bits of `data` (the buffer
    read from / about to be written at `file_offset`). The victim byte is
    `rule.at_offset - file_offset` when the rule pins an absolute file
    offset, else drawn from the rule's seeded RNG — deterministic per plan
    seed either way. A pinned offset that misses this buffer falls back to
    the seeded-random position: the firing was already counted, and a
    counted fault must never be a no-op (the PR 1 invariant). Models bit
    rot / a lying disk: no error surfaces, only wrong bytes."""
    buf = bytearray(data)
    if not buf:
        return bytes(buf)
    rule = ev.rule
    pos = None
    if rule.at_offset is not None:
        pos = rule.at_offset - file_offset
        if not 0 <= pos < len(buf):
            pos = None
    if pos is None:
        pos = ev.rng.randrange(len(buf))
    # flip N consecutive bit positions: distinct bits, so flips never cancel
    bitpos = pos * 8 + ev.rng.randrange(8)
    for i in range(max(1, rule.bits)):
        p = (bitpos + i) % (len(buf) * 8)
        buf[p // 8] ^= 1 << (p % 8)
    return bytes(buf)


# ------------------------------------------------ process-level faults --
#
# The seams above fire INSIDE a process; chaos soaks against real
# subprocess clusters (ops/proc_cluster.py) also need faults delivered TO
# processes: SIGKILL (machine loss), SIGSTOP/SIGCONT (a wedged or
# GC-storming peer — the process-level brownout), and kill+respawn
# (restart-with-recovery). A `ProcessFault` is one scheduled delivery; a
# schedule is generated deterministically from a seed with the same
# per-slot RNG discipline as FaultPlan rules, so a soak run's process
# chaos is bit-reproducible from (seed, targets, duration) alone. The
# schedule serializes like a plan (to_dict/from_dict) so the driver that
# owns the PIDs — never this module — executes it.

PROCESS_FAULT_KINDS = ("kill", "pause", "restart")


@dataclass
class ProcessFault:
    """One scheduled process-level fault.

    kind: "kill" (SIGKILL, no respawn), "pause" (SIGSTOP, SIGCONT after
    duration_s), "restart" (SIGKILL, respawn after duration_s, wait
    ready). target names a process in the owning cluster fixture
    ("volume-1"), at_s is seconds after schedule start."""

    at_s: float
    kind: str
    target: str
    duration_s: float = 0.0

    def to_dict(self) -> dict:
        d = {"at_s": self.at_s, "kind": self.kind, "target": self.target}
        if self.duration_s:
            d["duration_s"] = self.duration_s
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ProcessFault":
        return cls(
            at_s=float(d["at_s"]),
            kind=str(d["kind"]),
            target=str(d["target"]),
            duration_s=float(d.get("duration_s", 0.0)),
        )


def process_fault_schedule(
    seed: int,
    targets: list[str],
    duration_s: float,
    count: int = 3,
    kinds: tuple = PROCESS_FAULT_KINDS,
    start_s: float = 0.0,
    pause_s: float = 1.0,
    restart_s: float = 0.0,
) -> list[ProcessFault]:
    """Deterministic process-fault schedule: `count` faults over
    [start_s, duration_s), each drawn from its OWN seeded stream
    (Random(f"{seed}:proc:{i}")) so fault i's (time, kind, target) is
    independent of how many faults precede it — the FaultPlan per-rule
    discipline applied to the process dimension. Same arguments, same
    schedule, bit-for-bit; kinds cycle so every requested kind appears
    before any repeats (a 2-fault schedule over ("kill", "pause") always
    carries one of each — acceptance gates like ">= 1 SIGKILL" hold by
    construction, with the seed choosing victims and times)."""
    if not targets or count <= 0 or not kinds:
        return []
    faults = []
    span = max(duration_s - start_s, 0.0)
    for i in range(count):
        rng = Random(f"{seed}:proc:{i}")
        at = start_s + span * (i + rng.random()) / count
        kind = kinds[i % len(kinds)]
        f = ProcessFault(
            at_s=round(at, 3),
            kind=kind,
            target=rng.choice(list(targets)),
        )
        if kind == "pause":
            f.duration_s = round(pause_s * (0.5 + rng.random()), 3)
        elif kind == "restart":
            f.duration_s = round(restart_s, 3)
        faults.append(f)
    faults.sort(key=lambda f: (f.at_s, f.target, f.kind))
    return faults


def process_schedule_to_dicts(schedule: list[ProcessFault]) -> list[dict]:
    return [f.to_dict() for f in schedule]


def process_schedule_from_dicts(dicts: list[dict]) -> list[ProcessFault]:
    return [ProcessFault.from_dict(d) for d in dicts]


async def async_fault(
    plan: FaultPlan, op: str, target: str, timeout: Optional[float] = None
) -> Optional[FaultEvent]:
    """Event-loop seam (RPC/HTTP clients). latency sleeps then proceeds;
    reset raises ConnectionResetError; hang sleeps until the CALLER's
    per-call timeout (or the rule's delay, whichever is shorter; 30s when
    neither bounds it) then raises TimeoutError — the shape of a peer
    that accepted the connection and went silent, surfacing through the
    same deadline machinery a real hang would. http_error events are
    returned for the HTTP seam to synthesize a status; other seams treat
    them as resets."""
    ev = plan.match(op, target)
    if ev is None:
        return None
    kind = ev.kind
    if kind == "latency":
        await asyncio.sleep(ev.delay)
        return None
    if kind == "reset":
        raise ConnectionResetError(f"injected reset: {op} to {target}")
    if kind == "partition":
        # dropped both directions: surfaces as connection-refused, the
        # firewalled-peer shape (fast failure — the retry/breaker
        # machinery, not a timeout, decides what happens next)
        raise ConnectionError(f"injected partition: {op} to {target}")
    if kind == "hang":
        # the window-scaled effective delay, like latency (a ramped
        # windowed hang would otherwise silently ignore its ramp)
        bounds = [w for w in (ev.delay or None, timeout) if w is not None]
        await asyncio.sleep(min(bounds) if bounds else 30.0)
        raise TimeoutError(f"injected hang: {op} to {target}")
    if kind in ("eio",):
        raise injected_eio(target)
    return ev
