"""Store: per-server facade over disk locations; assembles heartbeats and
delta change queues (ref: weed/storage/store.go, store_ec.go)."""

from __future__ import annotations

import threading
from typing import Optional

from ..storage.erasure_coding.ec_volume import ShardBits
from .disk_location import DiskLocation
from .needle import Needle
from .super_block import ReplicaPlacement
from .ttl import TTL
from .volume import Volume


class Store:
    def __init__(
        self,
        ip: str,
        port: int,
        public_url: str,
        directories: list[str],
        max_volume_counts: list[int],
        needle_map_kind: str = "memory",
    ):
        self.ip = ip
        self.port = port
        self.public_url = public_url
        self.needle_map_kind = needle_map_kind
        self.locations = [
            DiskLocation(d, m, needle_map_kind=needle_map_kind)
            for d, m in zip(directories, max_volume_counts)
        ]
        self.volume_size_limit = 0  # set by master heartbeat response
        self._lock = threading.RLock()
        # delta queues drained into heartbeats (ref store.go:41-44)
        self.new_volumes: list[dict] = []
        self.deleted_volumes: list[dict] = []
        self.new_ec_shards: list[dict] = []
        self.deleted_ec_shards: list[dict] = []

    # --- lifecycle ---
    def load(self) -> None:
        for loc in self.locations:
            loc.load_existing_volumes()
            loc.load_all_ec_shards()

    def close(self) -> None:
        for loc in self.locations:
            loc.close()

    # --- volumes ---
    def find_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            v = loc.find_volume(vid)
            if v is not None:
                return v
        return None

    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    def add_volume(
        self,
        vid: int,
        collection: str,
        replication: str = "000",
        ttl_string: str = "",
        preallocate: int = 0,
    ) -> Volume:
        if self.find_volume(vid) is not None:
            raise ValueError(f"volume id {vid} already exists")
        location = max(
            self.locations, key=lambda l: l.max_volume_count - len(l.volumes)
        )
        v = Volume(
            location.directory,
            collection,
            vid,
            replica_placement=ReplicaPlacement.parse(replication),
            ttl=TTL.read(ttl_string),
            needle_map_kind=self.needle_map_kind,
        )
        location.add_volume(v)
        with self._lock:
            self.new_volumes.append(self._volume_message(v))
        return v

    def delete_volume(self, vid: int, keep_ec_files: bool = False) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        msg = self._volume_message(v)
        for loc in self.locations:
            if loc.delete_volume(vid, keep_ec_files=keep_ec_files):
                with self._lock:
                    self.deleted_volumes.append(msg)
                return True
        return False

    def unmount_volume(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        msg = self._volume_message(v)
        for loc in self.locations:
            if loc.unmount_volume(vid):
                with self._lock:
                    self.deleted_volumes.append(msg)
                return True
        return False

    def mount_volume(self, vid: int) -> bool:
        for loc in self.locations:
            count = loc.load_existing_volumes()
            v = loc.find_volume(vid)
            if v is not None:
                with self._lock:
                    self.new_volumes.append(self._volume_message(v))
                return True
        return False

    def mark_volume_readonly(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.no_write_or_delete = True
        return True

    # --- data path ---
    def write_volume_needle(self, vid: int, n: Needle, sync: bool = False):
        v = self.find_volume(vid)
        if v is None:
            raise LookupError(f"volume {vid} not found")
        if v.is_read_only():
            raise PermissionError(f"volume {vid} is read only")
        result = v.write_needle(n, sync=sync)
        if (
            self.volume_size_limit
            and v.data_file_size() > self.volume_size_limit
        ):
            # report full volume at next heartbeat via size field
            pass
        return result

    def read_volume_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise LookupError(f"volume {vid} not found")
        return v.read_needle(n)

    def delete_volume_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            return 0
        return v.delete_needle(n)

    # --- heartbeat assembly (ref store.go:194-254) ---
    def _volume_message(self, v: Volume) -> dict:
        return {
            "id": v.id,
            "size": v.data_file_size(),
            "collection": v.collection,
            "file_count": v.file_count(),
            "delete_count": v.deleted_count(),
            "deleted_byte_count": v.deleted_size(),
            "read_only": v.is_read_only(),
            "replica_placement": v.super_block.replica_placement.to_byte(),
            "version": v.version,
            "ttl": v.super_block.ttl.to_u32(),
            "compact_revision": v.super_block.compaction_revision,
            "modified_at_second": int(v.last_modified_ts_seconds),
            # anti-entropy fields: order-independent live-content digest +
            # append frontier let the master spot diverged/stale replicas
            # from heartbeats alone; scrub_corrupt marks a quarantined copy
            "content_digest": v.content_digest(),
            "append_at_ns": v.last_append_at_ns,
            "scrub_corrupt": v.scrub_corrupt,
            # vacuum plane: the garbage ratio rides every heartbeat so the
            # master's vacuum scheduler can rank candidates without an RPC
            # sweep (the per-dispatch VacuumVolumeCheck stays the
            # authoritative re-check)
            "garbage_ratio": round(v.garbage_level(), 4),
            # lifecycle plane: decayed access heat rides the same way, so
            # the master's lifecycle planner ranks hot/cold candidates
            # straight off heartbeats (VolumeLifecycleCheck re-checks
            # authoritatively at dispatch)
            "read_heat": round(v.heat.read_heat(), 4),
            "write_heat": round(v.heat.write_heat(), 4),
        }

    def collect_volume_digests(self) -> list[dict]:
        """Lightweight per-pulse digest refresh: full volume messages only
        travel at stream connect and on add/remove deltas, so steady-state
        writes would leave the master comparing stale digests. This slim
        message (id + digest + frontier + corrupt flag) rides every few
        heartbeat ticks instead."""
        out = []
        read_total = write_total = 0.0
        for loc in self.locations:
            for v in list(loc.volumes.values()):
                rh, wh = v.heat.read_heat(), v.heat.write_heat()
                read_total += rh
                write_total += wh
                out.append(
                    {
                        "id": v.id,
                        "content_digest": v.content_digest(),
                        "append_at_ns": v.last_append_at_ns,
                        "read_only": v.is_read_only(),
                        "scrub_corrupt": v.scrub_corrupt,
                        "garbage_ratio": round(v.garbage_level(), 4),
                        # lifecycle refresh: heat + size must stay current
                        # between full volume messages or the planner
                        # compares temperatures frozen at stream connect
                        "read_heat": round(rh, 4),
                        "write_heat": round(wh, 4),
                        "size": v.data_file_size(),
                    }
                )
        try:
            from ..util.metrics import VOLUME_HEAT

            VOLUME_HEAT.set(round(read_total, 4), kind="read")
            VOLUME_HEAT.set(round(write_total, 4), kind="write")
        except ImportError:
            pass
        return out

    def collect_heartbeat(self) -> dict:
        volume_messages = []
        max_volume_count = 0
        max_file_key = 0
        for loc in self.locations:
            max_volume_count += loc.max_volume_count
            for v in list(loc.volumes.values()):
                if v.max_file_key() > max_file_key:
                    max_file_key = v.max_file_key()
                volume_messages.append(self._volume_message(v))
        return {
            "ip": self.ip,
            "port": self.port,
            "public_url": self.public_url,
            "max_volume_count": max_volume_count,
            "max_file_key": max_file_key,
            "volumes": volume_messages,
            "has_no_volumes": len(volume_messages) == 0,
        }

    def collect_ec_heartbeat(self) -> dict:
        shard_messages = []
        for loc in self.locations:
            for vid, ev in loc.ec_volumes.items():
                # cold tier: ec_index_bits = local | offloaded — this
                # server still SERVES an offloaded shard (through the
                # remote read-through path), so lookup/read routing is
                # unchanged; the split rides alongside for the planner
                local = ev.shard_bits()
                offloaded = ev.offloaded_bits()
                shard_messages.append(
                    {
                        "id": vid,
                        "collection": ev.collection,
                        "ec_index_bits": local.plus(offloaded).bits,
                        "ec_local_bits": local.bits,
                        "ec_offloaded_bits": offloaded.bits,
                        "read_heat": round(ev.heat.read_heat(), 4),
                    }
                )
        return {
            "ec_shards": shard_messages,
            "has_no_ec_shards": len(shard_messages) == 0,
        }

    def collect_tier_manifest_keys(self) -> dict:
        """{backend_name: set(remote keys)} this server's durable tier
        records still name: EC `.ctm` manifest entries plus tiered
        volumes' .vif remote files — the orphan sweep's reference set
        (a remote object NO live manifest names is a leak, never data)."""
        out: dict[str, set] = {}
        for loc in self.locations:
            for ev in loc.ec_volumes.values():
                for ent in ev.remote_shards.values():
                    name = ent.get("backend", "")
                    key = ent.get("key", "")
                    if name and key:
                        out.setdefault(name, set()).add(key)
            for v in loc.volumes.values():
                info = getattr(v, "volume_info", None)
                if info is None:
                    continue
                for rf in getattr(info, "files", []):
                    name = f"{rf.backend_type}.{rf.backend_id}"
                    if rf.key:
                        out.setdefault(name, set()).add(rf.key)
        return out

    def collect_ec_heat(self) -> list[dict]:
        """Slim per-pulse EC heat refresh (the EC analogue of
        collect_volume_digests): full EC messages only travel every ~17
        ticks, far too slow for the lifecycle planner to notice a warm
        volume turning hot. One (id, read_heat) pair per local EC volume
        rides the anti-entropy tick instead."""
        out = []
        total = 0.0
        for loc in self.locations:
            for vid, ev in loc.ec_volumes.items():
                h = ev.heat.read_heat()
                total += h
                out.append(
                    {
                        "id": vid,
                        "collection": ev.collection,
                        "read_heat": round(h, 4),
                        # cold tier: the offload/recall planners rank off
                        # this same slim refresh (seconds-fresh, like the
                        # re-inflation sensor)
                        "ec_local_bits": ev.shard_bits().bits,
                        "ec_offloaded_bits": ev.offloaded_bits().bits,
                    }
                )
        try:
            from ..util.metrics import VOLUME_HEAT

            VOLUME_HEAT.set(round(total, 4), kind="ec_read")
        except ImportError:
            pass
        return out

    def note_volume_changed(self, old_msg: dict, new_msg: dict) -> None:
        """Queue an in-place layout change (e.g. replica placement rewrite)
        as a deleted(old)+new(new) delta pair; the master moves the volume
        between VolumeLayouts on the next pulse."""
        with self._lock:
            self.deleted_volumes.append(old_msg)
            self.new_volumes.append(new_msg)

    def drain_deltas(self) -> dict:
        with self._lock:
            # collapse same-vid churn within one pulse so the master's
            # delete-then-add processing can't resurrect ghosts:
            # - a volume we no longer hold must not appear as new
            #   (created+deleted within the tick)
            # - keep only the FIRST deleted msg (the layout the master has
            #   registered) and the LAST new msg (the current layout)
            held = {
                vid for loc in self.locations for vid in loc.volumes
            }
            new_by_vid: dict = {}
            for msg in self.new_volumes:
                if int(msg["id"]) in held:
                    new_by_vid[int(msg["id"])] = msg
            deleted_by_vid: dict = {}
            for msg in self.deleted_volumes:
                deleted_by_vid.setdefault(int(msg["id"]), msg)
            out = {
                "new_volumes": list(new_by_vid.values()),
                "deleted_volumes": list(deleted_by_vid.values()),
                "new_ec_shards": self.new_ec_shards,
                "deleted_ec_shards": self.deleted_ec_shards,
            }
            self.new_volumes = []
            self.deleted_volumes = []
            self.new_ec_shards = []
            self.deleted_ec_shards = []
            return out

    def note_ec_shards_changed(
        self, vid: int, collection: str, added: ShardBits, removed: ShardBits
    ) -> None:
        with self._lock:
            if added.bits:
                self.new_ec_shards.append(
                    {"id": vid, "collection": collection, "ec_index_bits": added.bits}
                )
            if removed.bits:
                self.deleted_ec_shards.append(
                    {"id": vid, "collection": collection, "ec_index_bits": removed.bits}
                )


# --- EC volume access (ref store_ec.go) ---
def _store_find_ec_volume(self, vid: int):
    for loc in self.locations:
        ev = loc.find_ec_volume(vid)
        if ev is not None:
            return ev
    return None


def _store_find_ec_shard(self, vid: int, shard_id: int):
    ev = self.find_ec_volume(vid)
    if ev is None:
        return None
    return ev.find_shard(shard_id)


Store.find_ec_volume = _store_find_ec_volume
Store.find_ec_shard = _store_find_ec_shard
