"""Group-commit write worker: batched fsync with truncate rollback.

The reference funnels fsync'd writes through a per-volume worker that
batches up to 4MB / 128 requests per fsync and, if the sync fails, truncates
the .dat back and fails every request in the batch
(ref: weed/storage/volume_read_write.go:290-363). This is the asyncio
re-design: writers enqueue (needle, future); the worker appends the whole
batch, fsyncs once, and resolves the futures — one disk flush amortized over
many concurrent writers.

Batch formation is ADAPTIVE, never timed: a batch is flushed the moment the
queue drains, so a lone writer pays zero added latency. The only widening
step is one event-loop yield before draining, taken only while the previous
batch proved there are concurrent writers in flight — that single pass lets
the wakeup's other writers enqueue, growing the batch without a fixed
window (a timed hold was measured strictly worse for the lookup gate at
every concurrency, and the same holds here).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Optional

from .needle import Needle
from .volume import Volume
from ..util import trace
from ..util.metrics import (
    GROUP_COMMIT_BATCH_SIZE,
    GROUP_COMMIT_FSYNCS,
    WRITE_STAGE_SECONDS,
)

MAX_BATCH_BYTES = 4 * 1024 * 1024
MAX_BATCH_REQUESTS = 128


@dataclass
class _Request:
    needle: Optional[Needle]
    is_write: bool
    future: asyncio.Future
    enqueued_at: float = 0.0
    # sampled trace context of the enqueuer, so the fsync-batch flush can
    # record one span linked to every member trace (ISSUE 8)
    ctx: object = None
    # multi-needle frame (ISSUE 13): the whole list appends as ONE
    # coalesced .dat extent + ONE .idx extent via write_needle_batch;
    # the future resolves with the per-needle result list
    needles: Optional[list] = None

    def data_bytes(self) -> int:
        if self.needles is not None:
            return sum(len(n.data) for n in self.needles)
        return len(self.needle.data)


class GroupCommitWorker:
    def __init__(self, volume: Volume):
        self.volume = volume
        self.queue: asyncio.Queue[_Request] = asyncio.Queue()
        self._task: Optional[asyncio.Task] = None
        # adaptive coalescing state: did the LAST flush see concurrency?
        self._concurrent = False
        self.stats = {"batches": 0, "requests": 0, "largest_batch": 0}

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None

    async def write(self, n: Needle) -> tuple[int, int, bool]:
        fut = asyncio.get_event_loop().create_future()
        await self.queue.put(
            _Request(
                n, True, fut, enqueued_at=time.perf_counter(),
                ctx=trace.current_sampled(),
            )
        )
        return await fut

    async def delete(self, n: Needle) -> int:
        fut = asyncio.get_event_loop().create_future()
        await self.queue.put(
            _Request(
                n, False, fut, enqueued_at=time.perf_counter(),
                ctx=trace.current_sampled(),
            )
        )
        return await fut

    async def write_many(self, needles: list) -> list:
        """Append a whole multi-needle frame through the worker: the
        frame lands as one .dat extent + one .idx extent
        (Volume.write_needle_batch) inside the shared fsync batch.
        Returns the per-needle result list (tuples or Exceptions)."""
        fut = asyncio.get_event_loop().create_future()
        await self.queue.put(
            _Request(
                None, True, fut, enqueued_at=time.perf_counter(),
                ctx=trace.current_sampled(), needles=needles,
            )
        )
        return await fut

    async def _run(self) -> None:
        while True:
            batch = [await self.queue.get()]
            if self.queue.empty() and self._concurrent:
                # adaptive widening: the previous flush proved writers are
                # arriving concurrently, so yield ONE loop pass to let this
                # wakeup's other writers enqueue before draining. When the
                # queue has already drained to a lone writer the yield is
                # skipped and the flush is immediate — no fixed window.
                await asyncio.sleep(0)
            bytes_queued = batch[0].data_bytes()
            # drain whatever is immediately available, bounded like the
            # reference's 4MB/128 limits
            while (
                bytes_queued < MAX_BATCH_BYTES
                and len(batch) < MAX_BATCH_REQUESTS
                and not self.queue.empty()
            ):
                req = self.queue.get_nowait()
                batch.append(req)
                bytes_queued += req.data_bytes()
            self._concurrent = len(batch) > 1 or not self.queue.empty()
            self.stats["batches"] += 1
            self.stats["requests"] += len(batch)
            if len(batch) > self.stats["largest_batch"]:
                self.stats["largest_batch"] = len(batch)
            GROUP_COMMIT_BATCH_SIZE.observe(len(batch))
            GROUP_COMMIT_FSYNCS.inc()
            members = [r.ctx for r in batch if r.ctx is not None]
            with trace.batch_span(
                "group_commit.flush", members,
                vid=self.volume.id, batch=len(batch),
            ):
                await asyncio.get_event_loop().run_in_executor(
                    None, self._commit_batch, batch
                )
            done = time.perf_counter()
            for req in batch:
                if req.enqueued_at:
                    WRITE_STAGE_SECONDS.observe(
                        done - req.enqueued_at, stage="group_commit_wait"
                    )

    def _commit_batch(self, batch: list[_Request]) -> None:
        v = self.volume
        end = v.data_backend.size()
        results: list[tuple[_Request, object]] = []
        for req in batch:
            try:
                if req.needles is not None:
                    out = v.write_needle_batch(req.needles)
                elif req.is_write:
                    out = v.write_needle(req.needle, sync=False)
                else:
                    out = v.delete_needle(req.needle)
                results.append((req, out))
            except Exception as e:  # per-request failure, batch continues
                results.append((req, e))
        try:
            v.data_backend.sync()
        except Exception as sync_err:
            # data past `end` is unreliable: roll back and fail the batch
            # (ref volume_read_write.go:344-355)
            try:
                v.data_backend.truncate(end)
            except Exception:
                pass
            results = [(req, sync_err) for req, _ in results]

        for req, out in results:
            if isinstance(out, Exception):
                req.future.get_loop().call_soon_threadsafe(
                    _fail_future, req.future, out
                )
            else:
                req.future.get_loop().call_soon_threadsafe(
                    _resolve_future, req.future, out
                )


def _resolve_future(fut: asyncio.Future, value) -> None:
    if not fut.done():
        fut.set_result(value)


def _fail_future(fut: asyncio.Future, exc: Exception) -> None:
    if not fut.done():
        fut.set_exception(exc)
