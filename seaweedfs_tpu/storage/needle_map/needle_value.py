"""NeedleValue: one index entry (ref: weed/storage/needle_map/needle_value.go)."""

from __future__ import annotations

from dataclasses import dataclass

from ...storage.idx import entry_to_bytes


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset_units: int  # actual offset // 8, as stored on disk
    size: int

    def to_bytes(self) -> bytes:
        return entry_to_bytes(self.key, self.offset_units, self.size)
