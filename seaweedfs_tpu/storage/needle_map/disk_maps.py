"""Disk-backed needle maps for low-memory volume servers.

Two variants mirroring the reference's non-memory mappers:

- `SqliteNeedleMap` — the LevelDB-class map (ref:
  weed/storage/needle_map_leveldb.go:27): key→(offset,size) lives in an
  on-disk B-tree (sqlite, stdlib — goleveldb's role) regenerated from the
  .idx log when stale; writes append to .idx first, then update the db.
- `SortedFileNeedleMap` — read-only binary-searchable sorted index (ref:
  weed/storage/needle_map_sorted_file.go:19): probes an .sdx file produced
  by sorting the .idx; Put is invalid, Delete tombstones in place.

Both recompute `MapMetric` by replaying the .idx
(ref: needle_map_metric.go newNeedleMapMetricFromIndexFile).
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Optional

import numpy as np

from ...types import TOMBSTONE_FILE_SIZE
from ..backend import DiskFile
from ..idx import entry_to_bytes, iter_index
from .metric import MapMetric
from .needle_value import NeedleValue


def metric_from_index_file(idx_path: str) -> MapMetric:
    """Replay the .idx log into counters (ref needle_map_metric.go:88-118)."""
    m = MapMetric()
    seen: dict[int, int] = {}
    if os.path.exists(idx_path):
        with open(idx_path, "rb") as f:
            for key, offset_units, size in iter_index(f):
                m.maybe_set_max_file_key(key)
                if offset_units > 0 and size != TOMBSTONE_FILE_SIZE:
                    m.log_put(key, seen.get(key, 0), size)
                    seen[key] = size
                else:
                    m.log_delete(seen.pop(key, 0))
    return m


class _MetricProperties:
    metric: MapMetric

    @property
    def file_count(self) -> int:
        return self.metric.file_count

    @property
    def deleted_count(self) -> int:
        return self.metric.deletion_count

    @property
    def content_size(self) -> int:
        return self.metric.content_size

    @property
    def deleted_size(self) -> int:
        return self.metric.deleted_size

    @property
    def max_file_key(self) -> int:
        return self.metric.maximum_file_key

    def snapshot(self):
        """Sorted (keys, offsets, sizes) columns for bulk TPU probes —
        same contract as CompactMap.snapshot."""
        keys, offs, sizes = [], [], []

        def visit(nv: NeedleValue) -> None:
            if nv.size != TOMBSTONE_FILE_SIZE:
                keys.append(nv.key)
                offs.append(nv.offset_units)
                sizes.append(nv.size)

        self.ascending_visit(visit)
        return (
            np.asarray(keys, dtype=np.uint64),
            np.asarray(offs, dtype=np.uint64),
            np.asarray(sizes, dtype=np.uint32),
        )


class SqliteNeedleMap(_MetricProperties):
    """LevelDB-class disk-backed mapper. The db file is `<base>.ldb`;
    freshness = db mtime newer than idx mtime (ref isLevelDbFresh)."""

    def __init__(self, idx_path: str):
        self.idx_path = idx_path
        self.db_path = idx_path[: -len(".idx")] + ".ldb"
        fresh = (
            os.path.exists(self.db_path)
            and os.path.exists(idx_path)
            and os.path.getmtime(self.db_path) > os.path.getmtime(idx_path)
        )
        self._idx = DiskFile(idx_path, create=True)
        # executor threads (group-commit fsync batches, vacuum) share this
        # connection; serialize access ourselves
        self._db_lock = threading.RLock()
        self.db = sqlite3.connect(self.db_path, check_same_thread=False)
        self.db.execute(
            "CREATE TABLE IF NOT EXISTS needles"
            " (key INTEGER PRIMARY KEY, offset INTEGER, size INTEGER)"
        )
        if not fresh:
            self._generate_db_from_idx()
        self.metric = metric_from_index_file(idx_path)
        self._mutations = 0

    def _generate_db_from_idx(self) -> None:
        self.db.execute("DELETE FROM needles")
        if os.path.exists(self.idx_path):
            with open(self.idx_path, "rb") as f:
                rows = []
                for key, offset_units, size in iter_index(f):
                    if offset_units > 0 and size != TOMBSTONE_FILE_SIZE:
                        rows.append((key, offset_units, size))
                    else:
                        # idx entries must apply strictly in order: flush
                        # buffered puts before the delete, or a
                        # put-then-delete of the same key inside one batch
                        # would resurrect the deleted needle
                        self._put_rows(rows)
                        rows = []
                        self.db.execute(
                            "DELETE FROM needles WHERE key=?", (key,)
                        )
                    if len(rows) >= 10000:
                        self._put_rows(rows)
                        rows = []
                self._put_rows(rows)
        self.db.commit()

    def _put_rows(self, rows) -> None:
        self.db.executemany(
            "INSERT OR REPLACE INTO needles VALUES (?,?,?)", rows
        )

    def put(self, key: int, offset_units: int, size: int) -> None:
        # idx first (ref LevelDbNeedleMap.Put: appendToIndexFile then db)
        with self._db_lock:
            old = self.db.execute(
                "SELECT size FROM needles WHERE key=?", (key,)
            ).fetchone()
            self._idx.append(entry_to_bytes(key, offset_units, size))
            self._put_rows([(key, offset_units, size)])
            self.metric.log_put(key, old[0] if old else 0, size)
            self._mutations += 1

    def put_batch(self, entries) -> None:
        """Many puts, one .idx append + one executemany (the batch
        append's map half for the leveldb-class mapper). A `pending`
        overlay keeps intra-batch duplicate keys honest: the deferred
        executemany means the SELECT alone would miss an earlier entry
        of the same batch and under-count the superseded copy's
        deletion bytes (the metric vacuum's garbage ratio feeds on)."""
        with self._db_lock:
            blob = bytearray()
            rows = []
            pending: dict = {}
            for key, offset_units, size in entries:
                old_size = pending.get(key)
                if old_size is None:
                    row = self.db.execute(
                        "SELECT size FROM needles WHERE key=?", (key,)
                    ).fetchone()
                    old_size = row[0] if row else 0
                blob += entry_to_bytes(key, offset_units, size)
                rows.append((key, offset_units, size))
                self.metric.log_put(key, old_size, size)
                pending[key] = size
                self._mutations += 1
            if blob:
                self._idx.append(bytes(blob))
                self._put_rows(rows)

    def get(self, key: int) -> Optional[NeedleValue]:
        with self._db_lock:
            row = self.db.execute(
                "SELECT offset, size FROM needles WHERE key=?", (key,)
            ).fetchone()
        if row is None:
            return None
        return NeedleValue(key=key, offset_units=row[0], size=row[1])

    def delete(self, key: int, offset_units: int) -> None:
        with self._db_lock:
            row = self.db.execute(
                "SELECT size FROM needles WHERE key=?", (key,)
            ).fetchone()
            self._idx.append(
                entry_to_bytes(key, offset_units, TOMBSTONE_FILE_SIZE)
            )
            self.db.execute("DELETE FROM needles WHERE key=?", (key,))
            self.metric.log_delete(row[0] if row else 0)
            self._mutations += 1

    def ascending_visit(self, visit) -> None:
        with self._db_lock:
            rows = list(
                self.db.execute(
                    "SELECT key, offset, size FROM needles ORDER BY key"
                )
            )
        for key, offset_units, size in rows:
            visit(NeedleValue(key=key, offset_units=offset_units, size=size))

    def snapshot_token(self) -> int:
        return self._mutations

    def index_file_size(self) -> int:
        return self._idx.size()

    def sync(self) -> None:
        with self._db_lock:
            self._idx.sync()
            self.db.commit()

    def close(self) -> None:
        with self._db_lock:
            self.db.commit()
            self.db.close()
        # mark the db fresh relative to the idx for the next open
        os.utime(self.db_path)
        self._idx.close()

    def destroy(self) -> None:
        self.close()
        for p in (self.idx_path, self.db_path):
            if os.path.exists(p):
                os.remove(p)


class SortedFileNeedleMap(_MetricProperties):
    """Read-only sorted-file mapper over `<base>.sdx`
    (ref: weed/storage/needle_map_sorted_file.go:19-108)."""

    def __init__(self, idx_path: str):
        from ..erasure_coding.ec_volume import NeedleNotFound  # noqa: F401
        from ..erasure_coding.encoder import write_sorted_file_from_idx

        self.idx_path = idx_path
        base = idx_path[: -len(".idx")]
        self.sdx_path = base + ".sdx"
        fresh = (
            os.path.exists(self.sdx_path)
            and os.path.exists(idx_path)
            and os.path.getmtime(self.sdx_path) > os.path.getmtime(idx_path)
        )
        if not fresh:
            write_sorted_file_from_idx(base, ".sdx")
        self._idx = DiskFile(idx_path, create=True)
        self._sdx = open(self.sdx_path, "r+b")
        self._sdx_size = os.path.getsize(self.sdx_path)
        self.metric = metric_from_index_file(idx_path)

    def _search(self, key: int, process_fn=None) -> Optional[tuple[int, int]]:
        from ..erasure_coding.ec_volume import (
            NeedleNotFound,
            search_needle_from_sorted_index,
        )

        try:
            return search_needle_from_sorted_index(
                self._sdx, self._sdx_size, key, process_fn
            )
        except NeedleNotFound:
            return None

    def put(self, key: int, offset_units: int, size: int) -> None:
        raise OSError("sorted-file needle map is read-only")

    def get(self, key: int) -> Optional[NeedleValue]:
        found = self._search(key)
        if found is None or found[1] == TOMBSTONE_FILE_SIZE:
            return None
        return NeedleValue(key=key, offset_units=found[0], size=found[1])

    def delete(self, key: int, offset_units: int) -> None:
        from ..erasure_coding.ec_volume import mark_needle_deleted

        found = self._search(key)
        if found is None or found[1] == TOMBSTONE_FILE_SIZE:
            return
        # idx first, then tombstone the .sdx entry in place
        self._idx.append(
            entry_to_bytes(key, offset_units, TOMBSTONE_FILE_SIZE)
        )
        self._search(key, mark_needle_deleted)
        self.metric.log_delete(found[1])
        self._mutations = getattr(self, "_mutations", 0) + 1

    def ascending_visit(self, visit) -> None:
        with open(self.sdx_path, "rb") as f:
            for key, offset_units, size in iter_index(f):
                visit(
                    NeedleValue(key=key, offset_units=offset_units, size=size)
                )

    def snapshot_token(self) -> int:
        return getattr(self, "_mutations", 0)

    def index_file_size(self) -> int:
        return self._idx.size()

    def sync(self) -> None:
        self._idx.sync()
        self._sdx.flush()
        os.fsync(self._sdx.fileno())

    def close(self) -> None:
        self._sdx.close()
        self._idx.close()

    def destroy(self) -> None:
        self.close()
        for p in (self.idx_path, self.sdx_path):
            if os.path.exists(p):
                os.remove(p)
