"""LsmNeedleMap: memory-bounded out-of-core needle map + instant mount.

The billion-needle problem (PAPER.md layer map): the reference ships
LevelDB and sorted-file needle maps precisely because a pure in-memory
map's resident bytes and its O(needles) mount replay are what cap
needles-per-server — lookup latency never was the limit. Our `memory`
kind (CompactMap) rebuilds a Python dict from the whole `.idx` at every
mount, and the seed-era `SqliteNeedleMap` regenerates its B-tree when
stale; both pay O(needles) wall before the first read.

This module is the LSM answer, built from parts the repo already
proves out:

- a SMALL in-memory memtable (dict) takes the write path, byte-bounded
  by ``SEAWEEDFS_TPU_NEEDLE_MAP_MB``;
- full memtables flush to immutable SORTED RUNS: flat columnar files
  (keys u64 | offsets u32/u64 | sizes u32, native little-endian) probed
  zero-copy through ``np.memmap`` + binary search — the `.ecx`
  machinery's shape, laid out as the flat device-friendly columns the
  TPU ``lookup_gate`` batch probes consume (arxiv 1202.3669's
  device-offload thesis applied to the needle index; flat pages in the
  spirit of arxiv 2604.15464);
- runs merge TIERED, smallest-adjacent-pair first, newest rank wins,
  tombstones dropped only when the merge includes rank 0 (the filer
  LSM's compaction discipline, `filer/lsm_store.py`);
- a crash-safe SNAPSHOT manifest (`<base>.nmm`, shadow-write + rename,
  torn shadows swept at load like the vacuum `.cpd/.cpx` sweep) records
  which `.idx` byte prefix the runs fold, so mount = mmap the runs +
  replay only the `.idx` TAIL past that frontier — O(tail), not
  O(needles). The `.idx` log stays the single durability authority:
  every put/delete appends there first, and a lost/garbage/stale
  snapshot only ever costs a (vectorized) full rebuild, never data.

Staleness binding: a manifest is honored only when (a) the `.idx` is at
least `idx_covered` bytes long, aligned, AND (b) the last index entry of
the covered prefix byte-matches the manifest's recorded copy. Paths that
REWRITE the `.idx` wholesale (vacuum commit, repair recopy, `weed fix`)
additionally call :func:`invalidate_snapshot` explicitly — the binding
is the belt-and-braces for a crash between the rewrite and the
invalidation.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Optional

import numpy as np

from ...types import (
    NEEDLE_CHECKSUM_SIZE,
    NEEDLE_HEADER_SIZE,
    NEEDLE_MAP_ENTRY_SIZE,
    NEEDLE_PADDING_SIZE,
    OFFSET_SIZE,
    TIMESTAMP_SIZE,
    TOMBSTONE_FILE_SIZE,
    VERSION3,
)
from ..backend import DiskFile
from ..idx import entry_to_bytes, parse_index_bytes
from .metric import MapMetric
from .needle_value import NeedleValue

# run file header: magic | version | offset width | pad | count | tombs
_RUN_MAGIC = b"SWNR"
_RUN_HEADER = struct.Struct("<4sBBHII")
assert _RUN_HEADER.size == 16

_OFF_DTYPE = np.dtype("<u4") if OFFSET_SIZE == 4 else np.dtype("<u8")
_TOMB = np.uint32(TOMBSTONE_FILE_SIZE)

MANIFEST_EXT = ".nmm"
RUN_EXT_PREFIX = ".nmr-"
BLOOM_EXT = ".bf"

# per-run bloom filters (ISSUE 15 satellite): built at seal from the
# run's key column, mmap'd at mount, consulted before the binary search
# so multi-run volumes skip searchsorted on absent keys. Purely an
# optimization sidecar: a missing/torn/mismatched .bf just means no
# filter for that run (and is swept with its run).
BLOOM_ENABLED = (
    os.environ.get("SEAWEEDFS_TPU_NEEDLE_MAP_BLOOM", "1") or "1"
) != "0"
BLOOM_BITS_PER_KEY = int(
    os.environ.get("SEAWEEDFS_TPU_NEEDLE_MAP_BLOOM_BITS", "10") or 10
)
# minimum run count before lookups consult the filters at all (ISSUE 17
# satellite, carried from PR 15): below it one searchsorted happens either
# way and the filter is pure overhead; deployments whose run shapes differ
# (e.g. many tiny runs with hot absent-key traffic) can lower/raise it
BLOOM_MIN_RUNS = int(
    os.environ.get("SEAWEEDFS_TPU_BLOOM_MIN_RUNS", "2") or 2
)
_BLOOM_MAGIC = b"SWBF"
_BLOOM_HEADER = struct.Struct("<4sBBHQI")  # magic|ver|k|pad|mbits|count
_BLOOM_BASE = _BLOOM_HEADER.size  # bitmap offset in the sidecar file
_M64 = (1 << 64) - 1


def _bloom_geometry(count: int) -> tuple[int, int]:
    """(mbits power-of-two, k hashes) for a run of `count` keys.

    k is pinned LOW (2) on purpose: the probe runs in scalar Python on
    the read path, so its cost scales with k while the saved work (one
    searchsorted page walk) is fixed — at >=10 bits/key, k=2 gives a
    ~3% false-positive rate, i.e. ~97% of absent probes skip the
    binary search for ~2 byte reads, which nets out far ahead of the
    information-theoretic-optimal k that would LOSE wall time here."""
    want = max(64, count * BLOOM_BITS_PER_KEY)
    mbits = 1 << (want - 1).bit_length()
    return mbits, 2


def _mix64_scalar(x: int) -> int:
    """murmur3 finalizer — the scalar twin of the vectorized build (the
    two MUST agree bit-for-bit or probes would miss live keys)."""
    x &= _M64
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & _M64
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & _M64
    x ^= x >> 33
    return x


def mix64_batch(keys: np.ndarray) -> np.ndarray:
    """Vectorized murmur3 finalizer — MUST agree bit-for-bit with
    `_mix64_scalar` (shared by the sidecar build, the ragged device
    kernel's host-side bloom addressing, and the scalar probe path)."""
    h = np.asarray(keys, dtype=np.uint64).copy()
    h ^= h >> np.uint64(33)
    h *= np.uint64(0xFF51AFD7ED558CCD)
    h ^= h >> np.uint64(33)
    h *= np.uint64(0xC4CEB9FE1A85EC53)
    h ^= h >> np.uint64(33)
    return h


def _write_bloom(run_path: str, keys: np.ndarray) -> None:
    """Sidecar `<run>.bf` built from the sealed run's key column
    (vectorized double hashing; tmp + rename so a torn write is never
    loaded)."""
    count = len(keys)
    mbits, k = _bloom_geometry(count)
    h = mix64_batch(keys)
    mask = np.uint64(mbits - 1)
    h1 = h & mask
    h2 = (h >> np.uint64(32)) | np.uint64(1)
    bits = np.zeros(mbits >> 3, dtype=np.uint8)
    for i in range(k):
        pos = (h1 + np.uint64(i) * h2) & mask
        np.bitwise_or.at(
            bits, (pos >> np.uint64(3)).astype(np.int64),
            (np.uint8(1) << (pos & np.uint64(7)).astype(np.uint8)),
        )
    tmp = run_path + BLOOM_EXT + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_BLOOM_HEADER.pack(_BLOOM_MAGIC, 1, k, 0, mbits, count))
        f.write(bits.tobytes())
    os.replace(tmp, run_path + BLOOM_EXT)

# resident-memory budget per volume map (the memtable bound); a dict
# entry (key int + 2-tuple of ints + table slot) measures ~120 bytes on
# CPython 3.10-3.12, so the default 4MB holds ~35k entries per volume
MEMTABLE_BYTES = int(
    float(os.environ.get("SEAWEEDFS_TPU_NEEDLE_MAP_MB", "4") or 4) * (1 << 20)
)
_ENTRY_COST = 120
MAX_RUNS = int(os.environ.get("SEAWEEDFS_TPU_NEEDLE_MAP_RUNS", "6") or 6)


# ---------------------------------------------------------------- metrics --
# module-level aggregates: per-map contributions keyed by id(map), summed
# into the needle_map_* gauges at flush/load/close events (never per-op)
_AGG_LOCK = threading.Lock()
_RESIDENT: dict[int, int] = {}
_RUN_COUNTS: dict[int, int] = {}


def _publish_aggregates() -> None:
    try:
        from ...util.metrics import (
            NEEDLE_MAP_RESIDENT_BYTES,
            NEEDLE_MAP_RUN_COUNT,
        )
    except ImportError:  # metrics registry unavailable (stripped builds)
        return
    with _AGG_LOCK:
        resident = sum(_RESIDENT.values())
        runs = sum(_RUN_COUNTS.values())
    NEEDLE_MAP_RESIDENT_BYTES.set(resident, kind="lsm")
    NEEDLE_MAP_RUN_COUNT.set(runs, kind="lsm")


def _drop_aggregates(map_id: int) -> None:
    with _AGG_LOCK:
        _RESIDENT.pop(map_id, None)
        _RUN_COUNTS.pop(map_id, None)
    _publish_aggregates()


# ------------------------------------------------------------ shared fold --


def fold_live_columns(
    keys: np.ndarray, offsets: np.ndarray, sizes: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Replay an .idx entry stream to its LIVE set, vectorized: each
    key's newest entry wins (np.unique over the reversed key column —
    the vacuum plane's idiom), keys whose newest entry is a tombstone
    drop out. Returns key-sorted (keys u64, offset_units, sizes u32).

    Shared by the LSM full rebuild, the EC encoder's sorted-file writer
    and the mount bench — one owner of "what does this log resolve to",
    with no Python dict materialized on the way.
    """
    n = len(keys)
    if n == 0:
        return (
            np.empty(0, dtype=np.uint64),
            np.empty(0, dtype=offsets.dtype),
            np.empty(0, dtype=np.uint32),
        )
    uniq_keys, rev_first = np.unique(keys[::-1], return_index=True)
    last = n - 1 - rev_first  # each key's newest entry
    off = offsets[last]
    sz = sizes[last]
    alive = (off != 0) & (sz != _TOMB)
    return uniq_keys[alive], off[alive], sz[alive]


def metric_from_columns(
    keys: np.ndarray, offsets: np.ndarray, sizes: np.ndarray
) -> MapMetric:
    """Exact vectorized equivalent of replaying the log through
    MapMetric (disk_maps.metric_from_index_file): every put counts into
    file_count/bytes; a put superseded by ANY later entry of its key is
    a deletion of its size (zero-size puts never count deletions, and
    tombstone appends count nothing of their own)."""
    m = MapMetric()
    n = len(keys)
    if n == 0:
        return m
    m.maximum_file_key = int(keys.max())
    put = (offsets != 0) & (sizes != _TOMB)
    m.file_count = int(put.sum())
    m.file_byte_count = int(sizes[put].astype(np.int64).sum())
    _uniq, rev_first = np.unique(keys[::-1], return_index=True)
    newest = np.zeros(n, dtype=bool)
    newest[n - 1 - rev_first] = True
    superseded = put & ~newest & (sizes > 0)
    m.deletion_count = int(superseded.sum())
    m.deletion_byte_count = int(sizes[superseded].astype(np.int64).sum())
    return m


def _record_ends(
    offsets: np.ndarray, sizes: np.ndarray, version: int
) -> np.ndarray:
    """Vectorized on-disk end offset of each entry's record (same
    arithmetic as volume.expected_dat_frontier)."""
    body = np.where(sizes == _TOMB, 0, sizes).astype(np.int64)
    base = (
        NEEDLE_HEADER_SIZE
        + body
        + NEEDLE_CHECKSUM_SIZE
        + (TIMESTAMP_SIZE if version == VERSION3 else 0)
    )
    return offsets.astype(np.int64) * NEEDLE_PADDING_SIZE + base + (
        8 - base % 8
    )


# ------------------------------------------------------------------- runs --


class _Run:
    """One immutable sorted run, mmap'd columnar: binary-searchable keys
    plus parallel offset/size columns. Tombstone entries (size ==
    TOMBSTONE_FILE_SIZE) shadow older runs until a rank-0 merge drops
    them; `tombs` in the header makes "pure live run" checkable without
    a scan (the zero-copy snapshot fast path)."""

    __slots__ = (
        "path", "count", "tombs", "keys", "offs", "sizes",
        "bloom", "bloom_k", "bloom_mbits", "bloom_probes", "bloom_neg",
        "_arena_seg",
    )

    def __init__(self, path: str):
        self.path = path
        self._arena_seg = None  # lazily-built DeviceColumnArena descriptor
        self.bloom = None
        self.bloom_k = 0
        self.bloom_mbits = 0
        self.bloom_probes = 0  # get() calls that consulted the filter
        self.bloom_neg = 0  # probes the filter short-circuited
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            head = f.read(_RUN_HEADER.size)
        magic, ver, offw, _pad, count, tombs = _RUN_HEADER.unpack(head)
        if magic != _RUN_MAGIC or ver != 1 or offw != _OFF_DTYPE.itemsize:
            raise ValueError(f"bad run header in {path}")
        expect = _RUN_HEADER.size + count * (8 + offw + 4)
        if size != expect:
            raise ValueError(f"run {path}: size {size} != expected {expect}")
        self.count = count
        self.tombs = tombs
        off = _RUN_HEADER.size
        self.keys = np.memmap(
            path, dtype="<u8", mode="r", offset=off, shape=(count,)
        )
        off += count * 8
        self.offs = np.memmap(
            path, dtype=_OFF_DTYPE, mode="r", offset=off, shape=(count,)
        )
        off += count * offw
        self.sizes = np.memmap(
            path, dtype="<u4", mode="r", offset=off, shape=(count,)
        )
        if BLOOM_ENABLED:
            self._load_bloom()

    def _load_bloom(self) -> None:
        path = self.path + BLOOM_EXT
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                head = f.read(_BLOOM_HEADER.size)
            magic, ver, k, _pad, mbits, count = _BLOOM_HEADER.unpack(head)
        except (OSError, struct.error):
            return
        if (
            magic != _BLOOM_MAGIC
            or ver != 1
            or k != 2  # get()'s probe is unrolled for exactly k=2: a
            # foreign/other-k sidecar would yield FALSE NEGATIVES
            # (live needles reported absent) — run unfiltered instead
            or count != self.count
            or mbits & (mbits - 1)
            or size != _BLOOM_HEADER.size + (mbits >> 3)
        ):
            return  # stale/torn/incompatible sidecar: no filter
        import mmap as _mmap

        with open(path, "rb") as f:
            # raw mmap, not np.memmap: the probe is SCALAR byte
            # indexing on the hot path, and numpy's per-index overhead
            # (~µs) would cost more than the searchsorted it skips —
            # mmap subscripting is tens of ns and still page-cache
            # backed, zero-copy
            self.bloom = _mmap.mmap(
                f.fileno(), 0, access=_mmap.ACCESS_READ
            )
        self.bloom_k = k
        self.bloom_mbits = mbits

    def _bloom_test(self, h: int) -> bool:
        """Filter membership from the PRE-MIXED hash (the caller mixes
        once per probe, however many runs consult it): k byte reads off
        the raw mmap plus a handful of int ops — cheaper than the
        searchsorted page walk it saves on absent keys."""
        mask = self.bloom_mbits - 1
        h1 = h & mask
        h2 = (h >> 32) | 1
        bits = self.bloom
        base = _BLOOM_HEADER.size
        for i in range(self.bloom_k):
            pos = (h1 + i * h2) & mask
            if not (bits[base + (pos >> 3)] & (1 << (pos & 7))):
                return False
        return True

    def get(
        self, key: int, bloom_hash: Optional[int] = None
    ) -> Optional[tuple[int, int]]:
        """(offset_units, size) — size may be the tombstone sentinel —
        or None when the key is not in this run. The filter is
        consulted only when the caller supplies the pre-mixed
        `bloom_hash` — a single-run map skips it entirely (nothing to
        shortcut: one search happens either way) and a multi-run probe
        mixes once for all runs. The k=2 test is INLINED and unrolled:
        a separate call per run would cost more than the searchsorted
        it skips."""
        bits = self.bloom
        if bits is not None and bloom_hash is not None:
            self.bloom_probes += 1
            mask = self.bloom_mbits - 1
            pos = bloom_hash & mask
            if not (bits[_BLOOM_BASE + (pos >> 3)] & (1 << (pos & 7))):
                self.bloom_neg += 1
                return None
            pos = (pos + ((bloom_hash >> 32) | 1)) & mask
            if not (bits[_BLOOM_BASE + (pos >> 3)] & (1 << (pos & 7))):
                self.bloom_neg += 1
                return None
        if self.count == 0:
            return None
        # the probe value MUST be np.uint64: a Python int against a u64
        # column has no safe common integer type, so numpy silently
        # promotes the WHOLE column to float64 — an O(n) copy per probe
        # (1.3ms at 2M entries) instead of an O(log n) binary search
        i = int(self.keys.searchsorted(np.uint64(key)))
        if i >= self.count or int(self.keys[i]) != key:
            return None
        return int(self.offs[i]), int(self.sizes[i])

    def columns(self):
        return self.keys, self.offs, self.sizes

    def arena_segment(self):
        """Immutable DeviceColumnArena descriptor for this run, built
        once and cached (runs never change content, so residency keyed
        by the descriptor's handle can never go stale). The bloom
        sidecar's bitmap rides along as a u32 word view over the same
        mmap — the device-side pre-filter for multi-run probes."""
        seg = self._arena_seg
        if seg is None:
            from ...ops.ragged_lookup import ArenaSegment

            bloom_words = None
            mbits = 0
            if self.bloom is not None and self.bloom_k == 2:
                bloom_words = np.frombuffer(
                    memoryview(self.bloom)[_BLOOM_BASE:], dtype="<u4"
                )
                mbits = self.bloom_mbits
            seg = self._arena_seg = ArenaSegment(
                keys=self.keys,
                offs=self.offs,
                sizes=self.sizes,
                bloom_words=bloom_words,
                bloom_mbits=mbits,
                source=self,
                # compaction closes superseded runs; the arena prunes
                # them at its next refresh instead of re-pinning forever
                alive=lambda run=self: run.keys is not None,
            )
        return seg

    def close(self) -> None:
        # np.memmap holds the mapping via ._mmap; dropping the views is
        # enough for the refcount, but close explicitly so a destroy()
        # on platforms with strict unlink semantics can proceed
        for col in (self.keys, self.offs, self.sizes):
            mm = getattr(col, "_mmap", None)
            if mm is not None:
                try:
                    mm.close()
                except (BufferError, ValueError):
                    pass  # another live view pins the mapping; gc owns it
        if self.bloom is not None:
            try:
                self.bloom.close()
            except (BufferError, ValueError):
                pass
        self.keys = self.offs = self.sizes = self.bloom = None


def _write_run(
    path: str, keys: np.ndarray, offs: np.ndarray, sizes: np.ndarray
) -> None:
    """Write one sorted run atomically (tmp + fsync + rename): a torn
    run can never carry a valid header+size pair, and an unreferenced
    `.tmp` is swept at load."""
    keys = np.ascontiguousarray(keys, dtype="<u8")
    offs = np.ascontiguousarray(offs, dtype=_OFF_DTYPE)
    sizes = np.ascontiguousarray(sizes, dtype="<u4")
    tombs = int((sizes == _TOMB).sum())
    head = _RUN_HEADER.pack(
        _RUN_MAGIC, 1, _OFF_DTYPE.itemsize, 0, len(keys), tombs
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(head)
        f.write(keys.tobytes())
        f.write(offs.tobytes())
        f.write(sizes.tobytes())
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    if BLOOM_ENABLED and len(keys):
        # sidecar AFTER the run is live: a crash between the two just
        # leaves a filterless run (correct, merely slower on absents)
        _write_bloom(path, keys)


# -------------------------------------------------------------- snapshots --


def _manifest_path(base: str) -> str:
    return base + MANIFEST_EXT


def _run_path(base: str, seq: int) -> str:
    return f"{base}{RUN_EXT_PREFIX}{seq}"


def sweep_snapshot_files(base: str, keep_seqs=()) -> int:
    """Remove run files (and manifest shadows) not named by `keep_seqs`
    — leftovers of an interrupted flush/merge, swept at load exactly
    like the vacuum compaction shadows. Returns how many were removed."""
    directory = os.path.dirname(base) or "."
    prefix = os.path.basename(base) + RUN_EXT_PREFIX
    keep = {f"{prefix}{seq}" for seq in keep_seqs} | {
        f"{prefix}{seq}{BLOOM_EXT}" for seq in keep_seqs
    }
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for fn in names:
        doomed = (
            (fn.startswith(prefix) and fn not in keep)
            or fn == os.path.basename(base) + MANIFEST_EXT + ".tmp"
        )
        if doomed:
            try:
                os.remove(os.path.join(directory, fn))
                removed += 1
            except OSError:
                pass
    return removed


def invalidate_snapshot(base: str) -> None:
    """Drop the persisted snapshot (manifest + every run) for a volume
    base. MUST be called by any path that rewrites the `.idx` wholesale
    — vacuum commit, repair recopy, `weed fix` — because the snapshot
    folds a byte prefix of the OLD log. Removing the manifest first
    makes the operation crash-safe: runs without a manifest are ignored
    and swept at the next load."""
    try:
        os.remove(_manifest_path(base))
    except FileNotFoundError:
        pass
    sweep_snapshot_files(base)


def _load_manifest(base: str) -> Optional[dict]:
    import msgpack

    path = _manifest_path(base)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            mf = msgpack.unpackb(f.read(), raw=False)
    except Exception:
        return None
    if not isinstance(mf, dict) or mf.get("version") != 1:
        return None
    if mf.get("offset_size") != OFFSET_SIZE:
        return None  # 4/5-byte offset variant flip: rebuild
    return mf


def _save_manifest(base: str, mf: dict) -> None:
    import msgpack

    path = _manifest_path(base)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(msgpack.packb(mf, use_bin_type=True))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ------------------------------------------------------------------- map --


class LsmNeedleMap:
    """Memory-bounded needle map: memtable + mmap'd sorted runs.

    Same observable contract as the other mappers (put/get/delete,
    ascending_visit, snapshot columns, MapMetric accessors); `get` of a
    deleted key returns a tombstone NeedleValue while the tombstone
    still shadows older runs and None once a rank-0 merge dropped it —
    callers already treat both as dead (the SqliteNeedleMap precedent).
    """

    def __init__(
        self,
        idx_path: str,
        version: int = VERSION3,
        memtable_bytes: int = 0,
        max_runs: int = 0,
    ):
        self.idx_path = idx_path
        self.base = idx_path[: -len(".idx")]
        self.version = version
        self.memtable_limit = max(
            1024, (memtable_bytes or MEMTABLE_BYTES) // _ENTRY_COST
        )
        self.max_runs = max_runs or MAX_RUNS
        self._lock = threading.RLock()
        self._mem: dict[int, tuple[int, int]] = {}
        self._runs: list[_Run] = []  # oldest .. newest
        self._seqs: list[int] = []
        self._next_seq = 1
        self._mutations = 0
        self._snapshot_cache: Optional[tuple] = None
        self._snapshot_token_at: int = -1
        # bytes of .idx the runs fold (the tail-replay frontier) and the
        # running max record end (the .dat frontier, monotone so it
        # survives tombstone-dropping merges)
        self._idx_covered = 0
        self._dat_frontier = 0
        self.metric = MapMetric()
        self._idx = DiskFile(idx_path, create=True)
        # load-time disclosure (the mount bench + metrics read these)
        self.loaded_from_snapshot = False
        self.tail_entries_replayed = 0
        self.snapshot_age_s = 0.0
        self._load()

    # ---------------- load / rebuild ----------------
    def _load(self) -> None:
        mf = _load_manifest(self.base)
        if mf is not None and self._try_load_snapshot(mf):
            self.loaded_from_snapshot = True
        else:
            invalidate_snapshot(self.base)
            self._rebuild_from_idx()
        self._note_resident()

    def _try_load_snapshot(self, mf: dict) -> bool:
        covered = int(mf.get("idx_covered", -1))
        idx_size = self._idx.size()
        if (
            covered < 0
            or covered % NEEDLE_MAP_ENTRY_SIZE != 0
            or covered > idx_size
        ):
            return False
        # last-entry binding: the covered prefix must be the SAME log
        # this manifest folded — a wholesale .idx rewrite (vacuum/fix/
        # repair) that dodged explicit invalidation fails here
        tail16 = mf.get("idx_tail16", b"") or b""
        if covered == 0:
            if tail16 != b"":
                return False
        else:
            got = self._idx.read_at(
                NEEDLE_MAP_ENTRY_SIZE, covered - NEEDLE_MAP_ENTRY_SIZE
            )
            if got != tail16:
                return False
        runs: list[_Run] = []
        try:
            for seq in mf.get("runs", []):
                runs.append(_Run(_run_path(self.base, int(seq))))
        except (OSError, ValueError):
            for r in runs:
                r.close()
            return False
        self._runs = runs
        self._seqs = [int(s) for s in mf.get("runs", [])]
        self._next_seq = (max(self._seqs) + 1) if self._seqs else 1
        self._idx_covered = covered
        self._dat_frontier = int(mf.get("dat_frontier", 0))
        met = mf.get("metric", {})
        self.metric = MapMetric(
            maximum_file_key=int(met.get("maximum_file_key", 0)),
            file_count=int(met.get("file_count", 0)),
            deletion_count=int(met.get("deletion_count", 0)),
            file_byte_count=int(met.get("file_byte_count", 0)),
            deletion_byte_count=int(met.get("deletion_byte_count", 0)),
        )
        self.snapshot_age_s = max(
            0.0, time.time() - float(mf.get("saved_at", 0.0))
        )
        sweep_snapshot_files(self.base, keep_seqs=self._seqs)
        # O(tail): replay only the entries past the fold frontier
        self._replay_tail(covered, idx_size)
        try:
            from ...util.metrics import (
                NEEDLE_MAP_SNAPSHOT_AGE,
                NEEDLE_MAP_TAIL_REPLAY,
            )

            NEEDLE_MAP_SNAPSHOT_AGE.set(
                round(self.snapshot_age_s, 3), kind="lsm"
            )
            if self.tail_entries_replayed:
                NEEDLE_MAP_TAIL_REPLAY.inc(self.tail_entries_replayed)
        except ImportError:
            pass
        return True

    def _replay_tail(self, start: int, idx_size: int) -> None:
        usable = idx_size - ((idx_size - start) % NEEDLE_MAP_ENTRY_SIZE)
        if usable <= start:
            return
        data = self._idx.read_at(usable - start, start)
        keys, offs, sizes = parse_index_bytes(data)
        ends = _record_ends(offs, sizes, self.version)
        positional = offs != 0
        if positional.any():
            self._dat_frontier = max(
                self._dat_frontier, int(ends[positional].max())
            )
        for key, off, size in zip(
            keys.tolist(), offs.tolist(), sizes.tolist()
        ):
            if off != 0 and size != TOMBSTONE_FILE_SIZE:
                old = self._probe(key)
                self._mem[key] = (off, size)
                self.metric.log_put(
                    key, old[1] if old is not None else 0, size
                )
            else:
                self.metric.maybe_set_max_file_key(key)
                old = self._probe(key)
                if old is not None and old[1] != TOMBSTONE_FILE_SIZE:
                    self.metric.log_delete(old[1])
                self._mem[key] = (off, TOMBSTONE_FILE_SIZE)
        self._mutations += 1
        self.tail_entries_replayed = len(keys)
        # re-assert the resident bound: a mount whose snapshot trailed
        # by more than a memtable's worth of entries would otherwise
        # park the whole tail in memory until the next put (which, on a
        # now-read-only volume, never comes). One flush AFTER the full
        # replay — a mid-replay flush would stamp idx_covered past
        # entries not yet applied.
        if len(self._mem) >= self.memtable_limit:
            self._flush_memtable()

    def _rebuild_from_idx(self) -> None:
        """Full vectorized rebuild: one sequential read of the log, one
        newest-wins fold, one live-only run — the no-snapshot mount path
        (still far cheaper than a per-entry dict replay, and it leaves
        the persisted snapshot behind so the NEXT mount is O(tail))."""
        idx_size = self._idx.size()
        usable = idx_size - (idx_size % NEEDLE_MAP_ENTRY_SIZE)
        self._runs = []
        self._seqs = []
        self._next_seq = 1
        self._mem = {}
        if usable:
            data = self._idx.read_at(usable, 0)
            keys, offs, sizes = parse_index_bytes(data)
            self.metric = metric_from_columns(keys, offs, sizes)
            ends = _record_ends(offs, sizes, self.version)
            positional = offs != 0
            self._dat_frontier = (
                int(ends[positional].max()) if positional.any() else 0
            )
            lk, lo, ls = fold_live_columns(keys, offs, sizes)
            if len(lk):
                seq = self._next_seq
                _write_run(_run_path(self.base, seq), lk, lo, ls)
                self._runs = [_Run(_run_path(self.base, seq))]
                self._seqs = [seq]
                self._next_seq = seq + 1
        else:
            self.metric = MapMetric()
            self._dat_frontier = 0
        self._idx_covered = usable
        self._mutations += 1
        self._persist_manifest()

    # ---------------- persistence ----------------
    def _persist_manifest(self) -> None:
        # the .idx prefix the manifest claims must be DURABLE before the
        # manifest names it (flushes are rare; this is not the write path)
        self._idx.sync()
        covered = self._idx_covered
        tail16 = (
            self._idx.read_at(
                NEEDLE_MAP_ENTRY_SIZE, covered - NEEDLE_MAP_ENTRY_SIZE
            )
            if covered
            else b""
        )
        _save_manifest(
            self.base,
            {
                "version": 1,
                "offset_size": OFFSET_SIZE,
                "runs": list(self._seqs),
                "idx_covered": covered,
                "idx_tail16": bytes(tail16),
                "dat_frontier": self._dat_frontier,
                "frontier_ns": 0,
                "metric": {
                    "maximum_file_key": self.metric.maximum_file_key,
                    "file_count": self.metric.file_count,
                    "deletion_count": self.metric.deletion_count,
                    "file_byte_count": self.metric.file_byte_count,
                    "deletion_byte_count": self.metric.deletion_byte_count,
                },
                "saved_at": time.time(),
            },
        )
        sweep_snapshot_files(self.base, keep_seqs=self._seqs)

    def _flush_memtable(self) -> None:
        """Memtable -> one sorted run (tombstones KEPT: they must shadow
        older runs) + manifest; then tiered merges until the run count
        fits. The manifest's fold frontier advances to the current .idx
        size — everything in the memtable came from entries before it."""
        if not self._mem:
            return
        items = sorted(self._mem.items())
        keys = np.fromiter(
            (k for k, _ in items), dtype=np.uint64, count=len(items)
        )
        offs = np.fromiter(
            (v[0] for _, v in items), dtype=_OFF_DTYPE, count=len(items)
        )
        sizes = np.fromiter(
            (v[1] for _, v in items), dtype=np.uint32, count=len(items)
        )
        seq = self._next_seq
        _write_run(_run_path(self.base, seq), keys, offs, sizes)
        self._runs.append(_Run(_run_path(self.base, seq)))
        self._seqs.append(seq)
        self._next_seq = seq + 1
        self._mem = {}
        self._idx_covered = self._idx.size()
        while len(self._runs) > self.max_runs:
            self._merge_smallest_adjacent()
        self._persist_manifest()
        self._note_resident()

    def _merge_smallest_adjacent(self) -> None:
        sizes = [r.count for r in self._runs]
        lo = min(range(len(sizes) - 1), key=lambda j: sizes[j] + sizes[j + 1])
        a, b = self._runs[lo], self._runs[lo + 1]
        keys = np.concatenate([np.asarray(a.keys), np.asarray(b.keys)])
        offs = np.concatenate([np.asarray(a.offs), np.asarray(b.offs)])
        szs = np.concatenate([np.asarray(a.sizes), np.asarray(b.sizes)])
        # newer rank (b) wins on key collision: b's entries come later in
        # the concatenation, so the reversed-unique fold picks them
        uniq, rev_first = np.unique(keys[::-1], return_index=True)
        last = len(keys) - 1 - rev_first
        mo, ms = offs[last], szs[last]
        if lo == 0:
            # nothing older left to shadow: tombstones drop here — and
            # ONLY here (a mid-stack tombstone must keep shadowing)
            alive = (mo != 0) & (ms != _TOMB)
            uniq, mo, ms = uniq[alive], mo[alive], ms[alive]
        seq = self._next_seq
        if len(uniq):
            _write_run(_run_path(self.base, seq), uniq, mo, ms)
            merged = [_Run(_run_path(self.base, seq))]
            merged_seqs = [seq]
            self._next_seq = seq + 1
        else:
            merged, merged_seqs = [], []
        old = self._runs[lo : lo + 2]
        self._runs[lo : lo + 2] = merged
        self._seqs[lo : lo + 2] = merged_seqs
        for r in old:
            r.close()
        # old run files are removed by the manifest-save sweep

    def _note_resident(self) -> None:
        with _AGG_LOCK:
            _RESIDENT[id(self)] = len(self._mem) * _ENTRY_COST
            _RUN_COUNTS[id(self)] = len(self._runs)
        _publish_aggregates()

    # ---------------- mapper contract ----------------
    def _probe(self, key: int) -> Optional[tuple[int, int]]:
        """(offset_units, size) from memtable else runs newest-first;
        tombstones included. None = absent everywhere. The bloom hash
        mixes ONCE here and every filtered run reuses it."""
        v = self._mem.get(key)
        if v is not None:
            return v
        runs = self._runs
        bh = None
        # below the (env-tunable) threshold maps skip filters outright
        multi = len(runs) >= BLOOM_MIN_RUNS
        for r in reversed(runs):
            if multi and bh is None and r.bloom is not None:
                bh = _mix64_scalar(key)
            hit = r.get(key, bh)
            if hit is not None:
                return hit
        return None

    def put(self, key: int, offset_units: int, size: int) -> None:
        with self._lock:
            old = self._probe(key)
            self._idx.append(entry_to_bytes(key, offset_units, size))
            self._set_mem(key, offset_units, size)
            self.metric.log_put(key, old[1] if old is not None else 0, size)

    def put_batch(self, entries) -> None:
        """Append MANY (key, offset_units, size) index entries in ONE
        .idx write — the multi-needle append satellite's map half (a
        batch frame costs one idx pwrite, not one per needle).

        No flush may fire MID-batch: a flush persists a manifest whose
        `idx_covered` is the current .idx size, so memtable state and
        the appended log must move in lock-step — the batch applies to
        the memtable WITHOUT the per-put flush trigger, the whole blob
        appends once, and the flush check runs at the end (either
        ordering of a mid-batch flush would otherwise let a crash strand
        a snapshot that disagrees with the durability-authority log)."""
        with self._lock:
            blob = bytearray()
            for key, offset_units, size in entries:
                old = self._probe(key)
                blob += entry_to_bytes(key, offset_units, size)
                self._set_mem_noflush(key, offset_units, size)
                self.metric.log_put(
                    key, old[1] if old is not None else 0, size
                )
            if blob:
                self._idx.append(bytes(blob))
            if len(self._mem) >= self.memtable_limit:
                self._flush_memtable()

    def _set_mem_noflush(
        self, key: int, offset_units: int, size: int
    ) -> None:
        self._mem[key] = (offset_units, size)
        self._mutations += 1
        # scalar twin of _record_ends: this runs per put at write QPS
        body = 0 if size == TOMBSTONE_FILE_SIZE else size
        rec = (
            NEEDLE_HEADER_SIZE
            + body
            + NEEDLE_CHECKSUM_SIZE
            + (TIMESTAMP_SIZE if self.version == VERSION3 else 0)
        )
        end = offset_units * NEEDLE_PADDING_SIZE + rec + (8 - rec % 8)
        if end > self._dat_frontier:
            self._dat_frontier = end

    def _set_mem(self, key: int, offset_units: int, size: int) -> None:
        self._set_mem_noflush(key, offset_units, size)
        if len(self._mem) >= self.memtable_limit:
            self._flush_memtable()

    def get(self, key: int) -> Optional[NeedleValue]:
        with self._lock:
            hit = self._probe(key)
        if hit is None:
            return None
        return NeedleValue(key=key, offset_units=hit[0], size=hit[1])

    def delete(self, key: int, offset_units: int) -> None:
        with self._lock:
            old = self._probe(key)
            self._idx.append(
                entry_to_bytes(key, offset_units, TOMBSTONE_FILE_SIZE)
            )
            self.metric.maybe_set_max_file_key(key)
            if old is not None and old[1] != TOMBSTONE_FILE_SIZE:
                self.metric.log_delete(old[1])
            self._set_mem(key, offset_units, TOMBSTONE_FILE_SIZE)

    # ---------------- snapshots / visits ----------------
    def _merged_columns(self, drop_tombstones: bool):
        """Key-sorted newest-wins fold of runs + memtable."""
        cols_k, cols_o, cols_s = [], [], []
        for r in self._runs:  # oldest .. newest
            cols_k.append(np.asarray(r.keys))
            cols_o.append(np.asarray(r.offs))
            cols_s.append(np.asarray(r.sizes))
        if self._mem:
            items = sorted(self._mem.items())
            cols_k.append(np.fromiter((k for k, _ in items), np.uint64))
            cols_o.append(np.fromiter((v[0] for _, v in items), _OFF_DTYPE))
            cols_s.append(np.fromiter((v[1] for _, v in items), np.uint32))
        if not cols_k:
            return (
                np.empty(0, np.uint64),
                np.empty(0, _OFF_DTYPE),
                np.empty(0, np.uint32),
            )
        keys = np.concatenate(cols_k)
        offs = np.concatenate(cols_o)
        sizes = np.concatenate(cols_s)
        uniq, rev_first = np.unique(keys[::-1], return_index=True)
        last = len(keys) - 1 - rev_first
        mo, ms = offs[last], sizes[last]
        if drop_tombstones:
            alive = (mo != 0) & (ms != _TOMB)
            return uniq[alive], mo[alive], ms[alive]
        return uniq, mo, ms

    def snapshot(self):
        """Sorted live (keys, offset_units, sizes) columns — the bulk-
        probe contract every mapper shares. A sealed map (one pure-live
        run, empty memtable) hands back the run's mmap'd columns
        ZERO-COPY: the lookup_gate's device snapshot and the EC path
        consume the on-disk pages directly, no dict and no copy."""
        with self._lock:
            if (
                self._snapshot_cache is not None
                and self._snapshot_token_at == self._mutations
            ):
                return self._snapshot_cache
            if (
                not self._mem
                and len(self._runs) == 1
                and self._runs[0].tombs == 0
            ):
                snap = self._runs[0].columns()
            else:
                snap = self._merged_columns(drop_tombstones=True)
            self._snapshot_cache = snap
            self._snapshot_token_at = self._mutations
            return snap

    def snapshot_token(self) -> int:
        return self._mutations

    def arena_view(self, keys):
        """One consistent view for a ragged device dispatch: under the
        map lock, probe the MEMTABLE host-side for every key (cheap dict
        hits; includes tombstones, which must shadow the runs) and hand
        back the current run set as newest-first arena descriptors. The
        two move together under the lock on purpose: a memtable flush
        between them would seal keys into a run the device batch never
        probes. Returns (mem_hits {key: (offset_units, size)}, segments
        newest-first) — segments is None when this map can't feed the
        arena (5-byte offsets exceed the kernel's u32 columns)."""
        if OFFSET_SIZE != 4:
            return {}, None
        with self._lock:
            mem = self._mem
            mem_hits = {}
            for k in keys:
                v = mem.get(int(k))
                if v is not None:
                    mem_hits[int(k)] = v
            segments = [r.arena_segment() for r in reversed(self._runs)]
        return mem_hits, segments

    def ascending_visit(self, visit) -> None:
        keys, offs, sizes = self._merged_columns(drop_tombstones=False)
        for key, off, size in zip(
            keys.tolist(), offs.tolist(), sizes.tolist()
        ):
            visit(NeedleValue(key=key, offset_units=off, size=size))

    # ---------------- frontiers ----------------
    def expected_dat_frontier(self, data_start: int) -> Optional[int]:
        """Where the .dat should end according to the log — computed
        from the running max the map already tracks (monotone across
        merges and tail replays), so the lsm mount path never re-reads
        the whole .idx the way volume.expected_dat_frontier must."""
        if self._dat_frontier == 0:
            return data_start if self.metric.file_count == 0 else None
        return self._dat_frontier

    # ---------------- admin ----------------
    def index_file_size(self) -> int:
        return self._idx.size()

    def sync(self) -> None:
        self._idx.sync()

    def save_snapshot(self) -> None:
        """Flush + persist now (clean close path): the next mount pays
        tail replay only for entries appended after this point."""
        with self._lock:
            if self._mem:
                self._flush_memtable()
            else:
                self._idx_covered = self._idx.size()
                self._persist_manifest()

    def close(self) -> None:
        with self._lock:
            try:
                self.save_snapshot()
            except OSError:
                pass  # worst case: next mount pays a full rebuild
            for r in self._runs:
                r.close()
            self._runs = []
            self._snapshot_cache = None
            self._idx.close()
        _drop_aggregates(id(self))

    def destroy(self) -> None:
        self.close()
        invalidate_snapshot(self.base)
        try:
            os.remove(self.idx_path)
        except FileNotFoundError:
            pass

    def bloom_stats(self) -> dict:
        """Aggregate per-run filter economics (the needle_map.lookup
        bench leg's disclosure): probes that consulted a filter, probes
        a filter short-circuited, how many runs carry one, the active
        consultation threshold, and the per-run consult/hit counts
        (newest run last, matching probe order reversed)."""
        with self._lock:
            probes = sum(r.bloom_probes for r in self._runs)
            neg = sum(r.bloom_neg for r in self._runs)
            filtered = sum(1 for r in self._runs if r.bloom is not None)
            per_run = [
                {
                    "probes": r.bloom_probes,
                    "negatives": r.bloom_neg,
                    "has_filter": r.bloom is not None,
                }
                for r in self._runs
            ]
        return {
            "runs": len(self._runs),
            "runs_with_filter": filtered,
            "min_runs": BLOOM_MIN_RUNS,
            "probes": probes,
            "negatives": neg,
            "filter_hit_rate": round(neg / probes, 4) if probes else 0.0,
            "per_run": per_run,
        }

    # metrics accessors mirroring the reference mapper
    @property
    def file_count(self) -> int:
        return self.metric.file_count

    @property
    def deleted_count(self) -> int:
        return self.metric.deletion_count

    @property
    def content_size(self) -> int:
        return self.metric.content_size

    @property
    def deleted_size(self) -> int:
        return self.metric.deleted_size

    @property
    def max_file_key(self) -> int:
        return self.metric.maximum_file_key


def new_lsm_needle_map(idx_path: str, version: int = VERSION3) -> LsmNeedleMap:
    """Fresh LSM map with a truncated idx and no snapshot."""
    base = idx_path[: -len(".idx")]
    invalidate_snapshot(base)
    f = DiskFile(idx_path, create=True)
    f.truncate(0)
    f.close()
    return LsmNeedleMap(idx_path, version=version)


def load_lsm_needle_map(
    idx_path: str, version: int = VERSION3
) -> LsmNeedleMap:
    """Open an existing volume's LSM map: snapshot + tail replay when
    the manifest binds to the current log, vectorized full rebuild
    otherwise."""
    return LsmNeedleMap(idx_path, version=version)
