"""Needle maps: fid -> (offset, size) indexes.

The mapper contract follows the reference's NeedleMapper interface
(ref: weed/storage/needle_map.go:21-34): Put/Get/Delete/AscendingVisit plus
metrics. Implementations here are designed TPU-first: every map can emit a
sorted-array snapshot (numpy u32 columns) consumed by the vectorized
bulk-lookup kernel in ops/index_kernel.py.
"""

from .needle_value import NeedleValue
from .compact_map import CompactMap
from .memdb import MemDb
from .metric import MapMetric
from .mapper import NeedleMap, new_needle_map, load_needle_map
from .lsm_map import (
    LsmNeedleMap,
    load_lsm_needle_map,
    new_lsm_needle_map,
)

__all__ = [
    "NeedleValue",
    "CompactMap",
    "MemDb",
    "MapMetric",
    "NeedleMap",
    "new_needle_map",
    "load_needle_map",
    "LsmNeedleMap",
    "load_lsm_needle_map",
    "new_lsm_needle_map",
]
