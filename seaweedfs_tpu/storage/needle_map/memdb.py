"""MemDb: sortable scratch needle map used by the EC encode path to turn a
.idx log into a sorted .ecx (ref: weed/storage/needle_map/memdb.go).

Unlike CompactMap, delete *removes* the entry (the reference deletes the
leveldb key, memdb.go:57-62).
"""

from __future__ import annotations

from typing import Callable, Optional

from ...types import TOMBSTONE_FILE_SIZE
from ..idx import iter_index
from .needle_value import NeedleValue


class MemDb:
    __slots__ = ("_map",)

    def __init__(self):
        self._map: dict[int, tuple[int, int]] = {}

    def set(self, key: int, offset_units: int, size: int) -> None:
        self._map[key] = (offset_units, size)

    def delete(self, key: int) -> None:
        self._map.pop(key, None)

    def get(self, key: int) -> Optional[NeedleValue]:
        v = self._map.get(key)
        if v is None:
            return None
        return NeedleValue(key=key, offset_units=v[0], size=v[1])

    def __len__(self) -> int:
        return len(self._map)

    def ascending_visit(self, visit: Callable[[NeedleValue], None]) -> None:
        for key in sorted(self._map):
            offset_units, size = self._map[key]
            visit(NeedleValue(key=key, offset_units=offset_units, size=size))

    def save_to_idx(self, path: str) -> None:
        with open(path, "wb") as f:
            for key in sorted(self._map):
                offset_units, size = self._map[key]
                f.write(NeedleValue(key, offset_units, size).to_bytes())

    def load_from_idx(self, path: str) -> None:
        """Replays a .idx log: live entries set, tombstones/zero-offset deleted
        (ref: ec_encoder.go:289-306 readNeedleMap)."""
        with open(path, "rb") as f:
            for key, offset_units, size in iter_index(f):
                if offset_units != 0 and size != TOMBSTONE_FILE_SIZE:
                    self.set(key, offset_units, size)
                else:
                    self.delete(key)
