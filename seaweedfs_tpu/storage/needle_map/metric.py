"""Map metrics (ref: weed/storage/needle_map_metric.go:13)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class MapMetric:
    maximum_file_key: int = 0
    file_count: int = 0
    deletion_count: int = 0
    file_byte_count: int = 0
    deletion_byte_count: int = 0

    def maybe_set_max_file_key(self, key: int) -> None:
        if key > self.maximum_file_key:
            self.maximum_file_key = key

    def log_put(self, key: int, old_size: int, new_size: int) -> None:
        self.maybe_set_max_file_key(key)
        self.file_count += 1
        self.file_byte_count += new_size
        if old_size > 0 and old_size != 0xFFFFFFFF:
            self.deletion_count += 1
            self.deletion_byte_count += old_size

    def log_delete(self, deleted_bytes: int) -> None:
        if deleted_bytes > 0:
            self.deletion_byte_count += deleted_bytes
            self.deletion_count += 1

    @property
    def content_size(self) -> int:
        return self.file_byte_count

    @property
    def deleted_size(self) -> int:
        return self.deletion_byte_count
