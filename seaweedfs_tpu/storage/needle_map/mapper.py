"""NeedleMap: CompactMap + append-only .idx log file.

Put/Delete mutate the in-memory map and append an entry to the .idx file;
Delete appends (key, offset, TOMBSTONE_FILE_SIZE)
(ref: weed/storage/needle_map.go:51-66, needle_map_memory.go).
"""

from __future__ import annotations

from typing import Optional

from ...types import TOMBSTONE_FILE_SIZE
from ..backend import DiskFile
from ..idx import entry_to_bytes, iter_index
from .compact_map import CompactMap
from .metric import MapMetric
from .needle_value import NeedleValue


class NeedleMap:
    def __init__(self, idx_path: str):
        self.m = CompactMap()
        self.metric = MapMetric()
        self.idx_path = idx_path
        self._idx = DiskFile(idx_path, create=True)

    def put(self, key: int, offset_units: int, size: int) -> None:
        _, old_size = self.m.set(key, offset_units, size)
        self.metric.log_put(key, old_size, size)
        self._idx.append(entry_to_bytes(key, offset_units, size))

    def put_batch(self, entries) -> None:
        """Apply many (key, offset_units, size) puts with ONE .idx
        append — the multi-needle batch append's map half."""
        blob = bytearray()
        for key, offset_units, size in entries:
            _, old_size = self.m.set(key, offset_units, size)
            self.metric.log_put(key, old_size, size)
            blob += entry_to_bytes(key, offset_units, size)
        if blob:
            self._idx.append(bytes(blob))

    def get(self, key: int) -> Optional[NeedleValue]:
        return self.m.get(key)

    def delete(self, key: int, offset_units: int) -> None:
        deleted_bytes = self.m.delete(key)
        self.metric.log_delete(deleted_bytes)
        self._idx.append(entry_to_bytes(key, offset_units, TOMBSTONE_FILE_SIZE))

    def ascending_visit(self, visit) -> None:
        self.m.ascending_visit(visit)

    def snapshot(self):
        return self.m.snapshot()

    def snapshot_token(self) -> int:
        return self.m.snapshot_token()

    def index_file_size(self) -> int:
        return self._idx.size()

    def sync(self) -> None:
        self._idx.sync()

    def close(self) -> None:
        self._idx.close()

    # metrics accessors mirroring the reference mapper
    @property
    def file_count(self) -> int:
        return self.metric.file_count

    @property
    def deleted_count(self) -> int:
        return self.metric.deletion_count

    @property
    def content_size(self) -> int:
        return self.metric.content_size

    @property
    def deleted_size(self) -> int:
        return self.metric.deleted_size

    @property
    def max_file_key(self) -> int:
        return self.metric.maximum_file_key


def new_needle_map(idx_path: str) -> NeedleMap:
    """Fresh map with a truncated idx file."""
    nm = NeedleMap(idx_path)
    nm._idx.truncate(0)
    return nm


def load_needle_map(idx_path: str) -> NeedleMap:
    """Rebuild the in-memory map by replaying the .idx log
    (ref: needle_map_memory.go LoadCompactNeedleMap/doLoading)."""
    nm = NeedleMap(idx_path)
    with open(idx_path, "rb") as f:
        for key, offset_units, size in iter_index(f):
            nm.metric.maybe_set_max_file_key(key)
            if offset_units > 0 and size != TOMBSTONE_FILE_SIZE:
                _, old_size = nm.m.set(key, offset_units, size)
                nm.metric.log_put(key, old_size, size)
            else:
                old_size = nm.m.delete(key)
                nm.metric.log_delete(old_size)
    return nm
