"""CompactMap: the hot in-memory needle index.

Observable semantics match the reference's sectioned sorted-array map
(ref: weed/storage/needle_map/compact_map.go): set returns the previous
(offset, size); delete tombstones the entry (size = TOMBSTONE_FILE_SIZE) and
returns the freed size; ascending_visit walks keys in order, including
tombstones.

The implementation is TPU-first rather than a translation: a Python dict is
the mutable write path, and a compacted sorted-column snapshot (numpy u64/u32
arrays) is maintained lazily for bulk probes — the same columns the Pallas
lookup kernel consumes. This replaces the reference's 100k-entry sections +
overflow lists; dict insertion keeps the amortized O(1) append property the
sections were built for.
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional

import numpy as np

from ...types import TOMBSTONE_FILE_SIZE
from .needle_value import NeedleValue


class CompactMap:
    __slots__ = ("_map", "_snapshot", "_dirty", "_mutations")

    def __init__(self):
        self._map: dict[int, tuple[int, int]] = {}
        self._snapshot: Optional[tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        self._dirty = True
        self._mutations = 0

    def set(self, key: int, offset_units: int, size: int) -> tuple[int, int]:
        """Insert/overwrite; returns (old_offset_units, old_size) — (0, 0) if new."""
        old = self._map.get(key)
        self._map[key] = (offset_units, size)
        self._dirty = True
        self._mutations += 1
        return old if old is not None else (0, 0)

    def delete(self, key: int) -> int:
        """Tombstone the key; returns the freed size (0 if absent/already dead)."""
        old = self._map.get(key)
        if old is None:
            return 0
        offset_units, size = old
        self._map[key] = (offset_units, TOMBSTONE_FILE_SIZE)
        self._dirty = True
        self._mutations += 1
        if size == TOMBSTONE_FILE_SIZE:
            return 0
        return size

    def snapshot_token(self) -> int:
        """Monotonic mutation counter: equal tokens mean snapshot() would
        return identical columns — the device-side cache key."""
        return self._mutations

    def get(self, key: int) -> Optional[NeedleValue]:
        v = self._map.get(key)
        if v is None:
            return None
        return NeedleValue(key=key, offset_units=v[0], size=v[1])

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: int) -> bool:
        return key in self._map

    def ascending_visit(self, visit: Callable[[NeedleValue], None]) -> None:
        for key in sorted(self._map):
            offset_units, size = self._map[key]
            visit(NeedleValue(key=key, offset_units=offset_units, size=size))

    def items_ascending(self) -> Iterator[NeedleValue]:
        for key in sorted(self._map):
            offset_units, size = self._map[key]
            yield NeedleValue(key=key, offset_units=offset_units, size=size)

    # --- TPU snapshot path ---
    def snapshot(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Sorted live entries as (keys u64[n], offset_units u32[n], sizes u32[n]).

        Tombstoned entries are excluded — this is the probe table for the
        bulk-lookup kernel; a miss there means not-found-or-deleted.
        """
        if self._dirty or self._snapshot is None:
            items = [
                (k, v[0], v[1])
                for k, v in self._map.items()
                if v[1] != TOMBSTONE_FILE_SIZE
            ]
            items.sort()
            if items:
                from ...types import OFFSET_SIZE

                arr = np.asarray(items, dtype=np.uint64)
                keys = arr[:, 0].astype(np.uint64)
                # u64 under the 5-byte-offset variant (units exceed u32)
                offsets = arr[:, 1].astype(
                    np.uint64 if OFFSET_SIZE == 5 else np.uint32
                )
                sizes = arr[:, 2].astype(np.uint32)
            else:
                keys = np.empty(0, dtype=np.uint64)
                offsets = np.empty(0, dtype=np.uint32)
                sizes = np.empty(0, dtype=np.uint32)
            self._snapshot = (keys, offsets, sizes)
            self._dirty = False
        return self._snapshot
