"""Volume super block — first 8 bytes of every .dat file.

Byte 0: version; byte 1: replica placement; bytes 2-3: TTL; bytes 4-5:
compaction revision; bytes 6-7: extra size (v2+, protobuf payload follows)
(ref: weed/storage/super_block/super_block.go:13-31,41-66).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..types import CURRENT_VERSION, VERSION2, VERSION3, bytes_to_u16, u16_to_bytes
from .ttl import EMPTY_TTL, TTL

SUPER_BLOCK_SIZE = 8


@dataclass(frozen=True)
class ReplicaPlacement:
    """xyz digits: x = other-DC copies, y = other-rack copies, z = same-rack
    copies (ref: weed/storage/super_block/replica_placement.go)."""

    same_rack_count: int = 0
    diff_rack_count: int = 0
    diff_data_center_count: int = 0

    @staticmethod
    def parse(s: str) -> "ReplicaPlacement":
        if len(s) > 3 or not s.isdigit() and s != "":
            raise ValueError(f"unknown replication type: {s!r}")
        s = (s or "000").zfill(3)
        rp = ReplicaPlacement(
            diff_data_center_count=int(s[0]),
            diff_rack_count=int(s[1]),
            same_rack_count=int(s[2]),
        )
        return rp

    @staticmethod
    def from_byte(b: int) -> "ReplicaPlacement":
        return ReplicaPlacement(
            diff_data_center_count=(b // 100) % 10,
            diff_rack_count=(b // 10) % 10,
            same_rack_count=b % 10,
        )

    def to_byte(self) -> int:
        # the xyz decimal encoding only fits a byte for single-digit
        # components summing under 256; the reference's Go byte()
        # conversion silently TRUNCATES larger placements
        # (replica_placement.go Byte()), corrupting e.g. "300" into 44 on
        # disk — raise instead, and reject out-of-digit components that
        # would alias another placement (1 dc + 15 racks reads back as
        # "250")
        for c in (
            self.diff_data_center_count,
            self.diff_rack_count,
            self.same_rack_count,
        ):
            if not 0 <= c <= 9:
                raise ValueError(
                    f"replica placement component out of range: {c}"
                )
        v = (
            self.diff_data_center_count * 100
            + self.diff_rack_count * 10
            + self.same_rack_count
        )
        if v > 255:
            raise ValueError(
                f"replica placement {self} does not fit the byte encoding"
            )
        return v

    def copy_count(self) -> int:
        return (
            self.diff_data_center_count + self.diff_rack_count + self.same_rack_count + 1
        )

    def __str__(self) -> str:
        return (
            f"{self.diff_data_center_count}{self.diff_rack_count}{self.same_rack_count}"
        )


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = EMPTY_TTL
    compaction_revision: int = 0
    extra: bytes = b""  # opaque protobuf payload

    def block_size(self) -> int:
        if self.version in (VERSION2, VERSION3):
            return SUPER_BLOCK_SIZE + len(self.extra)
        return SUPER_BLOCK_SIZE

    def to_bytes(self) -> bytes:
        header = bytearray(SUPER_BLOCK_SIZE)
        header[0] = self.version
        header[1] = self.replica_placement.to_byte()
        header[2:4] = self.ttl.to_bytes()
        header[4:6] = u16_to_bytes(self.compaction_revision)
        if self.extra:
            if len(self.extra) > 256 * 256 - 2:
                raise ValueError("super block extra too large")
            header[6:8] = u16_to_bytes(len(self.extra))
            return bytes(header) + self.extra
        return bytes(header)

    @staticmethod
    def parse(header: bytes) -> "SuperBlock":
        """Parse from >= 8 bytes; caller supplies extra bytes if extra_size > 0."""
        if len(header) < SUPER_BLOCK_SIZE:
            raise ValueError("cannot read super block: too short")
        version = header[0]
        if version not in (1, 2, 3):
            raise ValueError(f"unsupported super block version {version}")
        sb = SuperBlock(
            version=version,
            replica_placement=ReplicaPlacement.from_byte(header[1]),
            ttl=TTL.from_bytes(header[2:4]),
            compaction_revision=bytes_to_u16(header[4:6]),
        )
        extra_size = bytes_to_u16(header[6:8])
        if extra_size:
            sb.extra = header[SUPER_BLOCK_SIZE : SUPER_BLOCK_SIZE + extra_size]
            if len(sb.extra) != extra_size:
                raise ValueError("truncated super block extra")
        return sb


def read_super_block(backend_file) -> SuperBlock:
    header = backend_file.read_at(SUPER_BLOCK_SIZE, 0)
    sb = SuperBlock.parse(header)
    extra_size = bytes_to_u16(header[6:8])
    if extra_size:
        sb.extra = backend_file.read_at(extra_size, SUPER_BLOCK_SIZE)
    return sb
