"""Per-volume access heat: exponentially-decayed read/write op counters.

The lifecycle plane (docs/perf.md "Lifecycle plane") needs to know which
volumes are COLD enough to erasure-code into the warm tier and which EC
volumes turned HOT enough to re-inflate — the Haystack→f4 arc of the
reference paper, driven by observed access instead of operator commands.

The sensor is one `HeatTracker` per volume / EC volume: each read or
write op adds one unit of heat, and heat decays continuously in wall
time with a configurable half-life (`SEAWEEDFS_TPU_HEAT_HALFLIFE`,
default 600s). Folding happens at op time and at sample time, so the
value a heartbeat samples is

    H(t) = Σ_ops 0.5 ** ((t - t_op) / half_life)

— a function of the op timestamps ONLY. Heartbeat cadence, batching and
flush boundaries cannot change it (the order-independence property
test), which is what makes heat numbers comparable across servers with
different pulse phases: every server reports the same math over its own
op stream.

Persistence: `save()` writes a tiny JSON sidecar (`<base>.heat`) with the
decayed values anchored to wall-clock time; `load()` decays them forward
to now. A missing/corrupt sidecar means cold start (heat 0) — a restart
is never WORSE than cold start, and with a clean shutdown it is no worse
than no restart at all.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Optional


def default_half_life_s() -> float:
    try:
        v = float(os.environ.get("SEAWEEDFS_TPU_HEAT_HALFLIFE", "") or 600.0)
    except ValueError:
        return 600.0
    return v if v > 0 else 600.0


class HeatTracker:
    """Exponentially-decayed read/write op counters (one per volume).

    note_read/note_write fold the decay to `now` under a small dedicated
    lock (the serving hot path must not contend with the volume lock any
    longer than it already does), then add the op count. read_heat /
    write_heat sample without mutating history beyond the same fold.
    """

    __slots__ = (
        "half_life_s", "_clock", "_lock", "_read", "_write", "_at",
    )

    def __init__(
        self,
        half_life_s: Optional[float] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.half_life_s = (
            half_life_s if half_life_s is not None else default_half_life_s()
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._read = 0.0
        self._write = 0.0
        self._at = self._clock()

    # --- internals ---
    def _fold(self, now: float) -> None:
        dt = now - self._at
        if dt <= 0.0:
            return
        decay = 0.5 ** (dt / self.half_life_s)
        self._read *= decay
        self._write *= decay
        self._at = now

    # --- op accounting ---
    def note_read(self, n: float = 1.0, now: Optional[float] = None) -> None:
        with self._lock:
            self._fold(self._clock() if now is None else now)
            self._read += n

    def note_write(self, n: float = 1.0, now: Optional[float] = None) -> None:
        with self._lock:
            self._fold(self._clock() if now is None else now)
            self._write += n

    # --- sampling ---
    def read_heat(self, now: Optional[float] = None) -> float:
        with self._lock:
            self._fold(self._clock() if now is None else now)
            return self._read

    def write_heat(self, now: Optional[float] = None) -> float:
        with self._lock:
            self._fold(self._clock() if now is None else now)
            return self._write

    def seed(self, read: float, write: float = 0.0) -> None:
        """Overwrite the current heat (re-inflation hands the observed EC
        heat to the fresh volume so hysteresis survives the conversion)."""
        with self._lock:
            self._fold(self._clock())
            self._read = float(read)
            self._write = float(write)

    # --- persistence (sidecar <base>.heat) ---
    def save(self, path: str) -> None:
        now = self._clock()
        with self._lock:
            self._fold(now)
            blob = json.dumps(
                {
                    "read": self._read,
                    "write": self._write,
                    "at": now,
                    "half_life_s": self.half_life_s,
                }
            )
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass

    @classmethod
    def load(
        cls,
        path: str,
        half_life_s: Optional[float] = None,
        clock: Callable[[], float] = time.time,
    ) -> "HeatTracker":
        """Tracker restored from a sidecar, decayed forward from the save
        timestamp; cold start on a missing/unreadable/garbage sidecar."""
        t = cls(half_life_s=half_life_s, clock=clock)
        try:
            with open(path) as f:
                d = json.load(f)
            read, write = float(d["read"]), float(d["write"])
            at = float(d["at"])
        except (OSError, ValueError, KeyError, TypeError):
            return t
        now = clock()
        if at > now:  # clock skew / bad sidecar: never inflate history
            at = now
        decay = 0.5 ** ((now - at) / t.half_life_s)
        with t._lock:
            t._read = max(read, 0.0) * decay
            t._write = max(write, 0.0) * decay
            t._at = now
        return t
