"""DiskLocation: one data directory of volumes and EC shards
(ref: weed/storage/disk_location.go, disk_location_ec.go)."""

from __future__ import annotations

import os
import re
import threading
from typing import Dict, Optional

from .erasure_coding import to_ext
from .erasure_coding.ec_volume import EcVolume, EcVolumeShard
from .volume import Volume

_DAT_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.dat$")
_VIF_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.vif$")
_EC_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.ec(?P<shard>[0-9][0-9])$")
_CTM_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.ctm$")


def parse_volume_file_name(name: str) -> Optional[tuple[str, int]]:
    m = _DAT_RE.match(name)
    if not m:
        return None
    return m.group("collection") or "", int(m.group("vid"))


class DiskLocation:
    def __init__(
        self,
        directory: str,
        max_volume_count: int = 7,
        needle_map_kind: str = "memory",
    ):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_volume_count = max_volume_count
        self.needle_map_kind = needle_map_kind
        self.volumes: Dict[int, Volume] = {}
        self.ec_volumes: Dict[int, EcVolume] = {}
        self._lock = threading.RLock()

    # --- normal volumes ---
    def _discover_volume_names(self) -> list[tuple[str, int]]:
        """Candidate (collection, vid) pairs: .dat files plus .vif sidecars —
        a tiered volume has no local .dat (ref volume_tier.go), only
        .idx + .vif naming the remote copy."""
        found: list[tuple[str, int]] = []
        seen: set[tuple[str, int]] = set()
        for name in sorted(os.listdir(self.directory)):
            m = _DAT_RE.match(name) or _VIF_RE.match(name)
            if m is None:
                continue
            parsed = (m.group("collection") or "", int(m.group("vid")))
            if parsed not in seen:
                seen.add(parsed)
                found.append(parsed)
        return found

    def load_existing_volumes(self) -> int:
        count = 0
        for collection, vid in self._discover_volume_names():
            with self._lock:
                if vid in self.volumes:
                    continue
                try:
                    v = Volume(
                        self.directory,
                        collection,
                        vid,
                        create=False,
                        needle_map_kind=self.needle_map_kind,
                    )
                except FileNotFoundError:
                    continue
                except Exception:
                    continue
                self.volumes[vid] = v
                count += 1
        return count

    def add_volume(self, v: Volume) -> None:
        with self._lock:
            self.volumes[v.id] = v

    def find_volume(self, vid: int) -> Optional[Volume]:
        with self._lock:
            return self.volumes.get(vid)

    def delete_volume(self, vid: int, keep_ec_files: bool = False) -> bool:
        with self._lock:
            v = self.volumes.pop(vid, None)
        if v is None:
            return False
        v.destroy(keep_ec_files=keep_ec_files)
        return True

    def unmount_volume(self, vid: int) -> bool:
        with self._lock:
            v = self.volumes.pop(vid, None)
        if v is None:
            return False
        v.close()
        return True

    def close(self) -> None:
        with self._lock:
            for v in self.volumes.values():
                v.close()
            for ev in self.ec_volumes.values():
                ev.close()
            self.volumes.clear()
            self.ec_volumes.clear()

    # --- EC shards (ref disk_location_ec.go) ---
    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        with self._lock:
            return self.ec_volumes.get(vid)

    def load_ec_shard(self, collection: str, vid: int, shard_id: int) -> EcVolumeShard:
        shard = EcVolumeShard(self.directory, collection, vid, shard_id)
        with self._lock:
            ev = self.ec_volumes.get(vid)
            if ev is None:
                ev = EcVolume(self.directory, collection, vid)
                self.ec_volumes[vid] = ev
            ev.add_shard(shard)
        return shard

    def load_cold_ec_volume(self, collection: str, vid: int) -> Optional[EcVolume]:
        """Mount an EC volume whose shard files live entirely on the
        remote tier (`.ecx` + `.ctm`, zero local `.ecNN`) — reads serve
        through the read-through cache until heat recalls the shards."""
        with self._lock:
            ev = self.ec_volumes.get(vid)
            if ev is not None:
                return ev
            try:
                ev = EcVolume(self.directory, collection, vid)
            except (FileNotFoundError, OSError):
                return None
            if not ev.remote_shards:
                ev.close()
                return None
            self.ec_volumes[vid] = ev
            return ev

    def unload_ec_shard(self, vid: int, shard_id: int) -> bool:
        with self._lock:
            ev = self.ec_volumes.get(vid)
            if ev is None:
                return False
            shard = ev.delete_shard(shard_id)
            if shard is None:
                return False
            shard.close()
            # a volume with shards on the remote tier stays mounted: it
            # still serves reads (through the cold cache) and must keep
            # heartbeating its offloaded bits
            if not ev.shards and not ev.remote_shards:
                ev.close()
                del self.ec_volumes[vid]
            return True

    def load_all_ec_shards(self) -> int:
        """Discover .ecNN files with a matching .ecx (ref
        disk_location_ec.go:115-161)."""
        count = 0
        for name in sorted(os.listdir(self.directory)):
            m = _EC_RE.match(name)
            if not m:
                continue
            collection = m.group("collection") or ""
            vid = int(m.group("vid"))
            shard_id = int(m.group("shard"))
            base = (
                os.path.join(self.directory, f"{collection}_{vid}")
                if collection
                else os.path.join(self.directory, str(vid))
            )
            if not os.path.exists(base + ".ecx"):
                continue
            # a .dat alongside means the volume is not yet converted; the
            # reference still loads the shard and lets the server choose
            try:
                self.load_ec_shard(collection, vid, shard_id)
                count += 1
            except Exception:
                continue
        # cold tier: volumes whose every shard is offloaded leave no .ecNN
        # behind — discover them via the .ctm manifest + .ecx pair
        for name in sorted(os.listdir(self.directory)):
            m = _CTM_RE.match(name)
            if not m:
                continue
            collection = m.group("collection") or ""
            vid = int(m.group("vid"))
            base = (
                os.path.join(self.directory, f"{collection}_{vid}")
                if collection
                else os.path.join(self.directory, str(vid))
            )
            if vid in self.ec_volumes or not os.path.exists(base + ".ecx"):
                continue
            if self.load_cold_ec_volume(collection, vid) is not None:
                count += 1
        return count
