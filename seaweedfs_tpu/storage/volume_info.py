""".vif sidecar: volume info persisted as JSON.

The reference writes VolumeInfo as jsonpb (ref: weed/pb/volume_info.go:55-76,
message at volume_server.proto:376-380), so plain JSON with camelCase keys is
format-compatible: {"files": [...], "version": N, "replication": "xyz"}.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class RemoteFile:
    backend_type: str = ""
    backend_id: str = ""
    key: str = ""
    offset: int = 0
    file_size: int = 0
    modified_time: int = 0
    extension: str = ""


@dataclass
class VolumeInfo:
    files: list[RemoteFile] = field(default_factory=list)
    version: int = 0
    replication: str = ""
    # RS geometry of the EC shards (0 = the default 10.4); our extension —
    # the reference fixes the geometry at compile time (ec_encoder.go:17-23)
    data_shards: int = 0
    parity_shards: int = 0


def load_volume_info(path: str) -> VolumeInfo | None:
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    info = VolumeInfo(
        version=int(d.get("version", 0)),
        replication=d.get("replication", ""),
        data_shards=int(d.get("dataShards", 0)),
        parity_shards=int(d.get("parityShards", 0)),
    )
    for fd in d.get("files", []) or []:
        info.files.append(
            RemoteFile(
                backend_type=fd.get("backendType", ""),
                backend_id=fd.get("backendId", ""),
                key=fd.get("key", ""),
                offset=int(fd.get("offset", 0)),
                file_size=int(fd.get("fileSize", 0)),
                modified_time=int(fd.get("modifiedTime", 0)),
                extension=fd.get("extension", ""),
            )
        )
    return info


def save_volume_info(path: str, info: VolumeInfo) -> None:
    d = {
        "files": [
            {
                "backendType": f.backend_type,
                "backendId": f.backend_id,
                "key": f.key,
                "offset": f.offset,
                "fileSize": f.file_size,
                "modifiedTime": f.modified_time,
                "extension": f.extension,
            }
            for f in info.files
        ],
        "version": info.version,
        "replication": info.replication,
    }
    if info.data_shards:
        d["dataShards"] = info.data_shards
        d["parityShards"] = info.parity_shards
    with open(path, "w") as f:
        json.dump(d, f, indent=2)
