"""Tiered (cloud) storage backends: move warm volume .dat files off local
disk and serve reads through the remote store.

Mirrors the reference's backend registry + S3 tiering
(ref: weed/storage/backend/backend.go:25-101,
weed/storage/backend/s3_backend/s3_backend.go): a `BackendStorage`
produces read-only `BackendStorageFile`s addressed by key, and supports
copy-in/download-out/delete with progress callbacks.

Two backends ship:
- "local": a directory standing in for a remote object store — the
  fully-offline tier used in tests and single-host deployments.
- "s3": any S3-compatible HTTP endpoint (including this framework's own
  S3 gateway), via stdlib urllib so the synchronous volume read path can
  call it without touching an event loop. Unsigned requests; for real
  AWS put signing credentials in front (no egress in this environment).

Remote-call discipline (ISSUE 12 satellite): every S3-backend HTTP call
runs through `_sync_retry` — the synchronous sibling of
`util/backoff.retry_async` — with bounded attempts, full-jitter sleeps,
an absolute per-operation deadline that both shrinks each attempt's
socket timeout and refuses attempts it cannot finish, the peer's
``Retry-After`` honored as a sleep floor on 429/503 (both the
delta-seconds and HTTP-date spellings, via
`util/fasthttp.parse_retry_after`), and the process-wide `RetryBudget`
(failures withdraw, a dry bucket suppresses further retries) so a sick
remote tier cannot amplify into a retry storm from the volume path.
"""

from __future__ import annotations

import os
import random
import shutil
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

from ..util import faults as _faults
from ..util.backoff import (
    BackoffPolicy,
    deadline_after,
    remaining,
    shared_retry_budget,
)

ProgressFn = Optional[Callable[[int, float], None]]

_COPY_CHUNK = 1 << 20

# per-operation wall deadlines (seconds): reads/deletes are volume-path
# latencies, transfers are bulk lifecycle I/O
_READ_DEADLINE_S = 60.0
_TRANSFER_DEADLINE_S = 600.0
_RETRY_POLICY = BackoffPolicy(base=0.1, cap=5.0, attempts=4)


def _consult_remote_faults(
    method: str, url: str, timeout: Optional[float] = None
) -> None:
    """Client-side fault seam for the synchronous urllib remote-tier
    path (ISSUE 14 satellite): the same `FaultPlan` rules that brownout
    the async clients fire here, with op ``http:<METHOD>`` and the
    remote endpoint's host:port as target — so cold-tier chaos tests are
    seed-deterministic like every other plane (docs/robustness.md fault
    matrix, row "remote"). Injected shapes map onto what urllib would
    really raise: reset/partition -> URLError(ConnectionResetError),
    hang -> sleeps out the caller's socket timeout then
    URLError(TimeoutError), http_error -> HTTPError(status) with
    Retry-After on shed-shaped statuses (exercising `_sync_retry`'s
    honor path), latency sleeps, crash kills the plan (SimulatedCrash
    thereafter, like every sync seam)."""
    plan = _faults._PLAN
    if plan is None:
        return
    target = urllib.parse.urlsplit(url).netloc or url
    ev = plan.match(f"http:{method}", target)
    if ev is None:
        return
    kind = ev.kind
    if kind == "latency":
        time.sleep(ev.delay)
        return
    if kind == "crash":
        plan.mark_dead()
        raise _faults.SimulatedCrash(f"crash in http:{method} to {target}")
    if kind == "http_error":
        import email.message

        hdrs = email.message.Message()
        if ev.rule.status in (429, 503):
            hdrs["Retry-After"] = "1"
        raise urllib.error.HTTPError(
            url, ev.rule.status, "injected fault", hdrs, None
        )
    if kind == "hang":
        bounds = [w for w in (ev.delay or None, timeout) if w is not None]
        time.sleep(min(bounds) if bounds else 30.0)
        raise urllib.error.URLError(
            TimeoutError(f"injected hang: http:{method} to {target}")
        )
    if kind in ("reset", "partition"):
        raise urllib.error.URLError(
            ConnectionResetError(f"injected {kind}: {target}")
        )
    raise urllib.error.URLError(_faults.injected_eio(target))


def _retryable(e: BaseException) -> bool:
    if isinstance(e, urllib.error.HTTPError):
        # 5xx/429: the peer may heal; other 4xx are deterministic
        return e.code in (429, 500, 502, 503, 504)
    return isinstance(e, (urllib.error.URLError, TimeoutError, OSError))


def _sync_retry(
    fn: Callable[[float], object],
    op: str,
    deadline_s: float,
    policy: BackoffPolicy = _RETRY_POLICY,
    rng=None,
):
    """Run `fn(attempt_timeout_s)` with bounded, budgeted, deadlined
    retries. `fn` receives the REMAINING wall budget as its socket
    timeout, so a slow first attempt shrinks every later one and the
    operation as a whole respects `deadline_s`."""
    from ..util.fasthttp import parse_retry_after

    rng = rng or random
    deadline = deadline_after(deadline_s)
    budget = shared_retry_budget()
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        try:
            out = fn(remaining(deadline, default=30.0))
        except Exception as e:
            if not _retryable(e):
                raise
            last = e
            if budget is not None:
                budget.on_failure()
        else:
            if budget is not None:
                # deposit: urllib is its own transport here — nothing
                # else feeds the budget for these calls (the async
                # clients deposit in FastHTTPClient.request/Stub.call)
                budget.on_success()
            return out
        if attempt == policy.attempts - 1:
            break
        if budget is not None and not budget.allow(op):
            break
        d = policy.delay(attempt, rng)
        if (
            isinstance(last, urllib.error.HTTPError)
            and last.code in (429, 503)
            and last.headers is not None
        ):
            ra = last.headers.get("Retry-After")
            if ra:
                floor = parse_retry_after(ra.encode("latin1"))
                if floor:
                    # the peer asked for breathing room: jitter must not
                    # undercut it (capped — the deadline still wins)
                    d = max(d, min(floor, policy.cap))
        left = remaining(deadline)
        if left is not None:
            if left <= 0.002:
                break
            d = min(d, left)
        time.sleep(d)
    assert last is not None
    raise last


class BackendStorage:
    storage_type = ""

    def __init__(self, backend_id: str):
        self.id = backend_id

    @property
    def name(self) -> str:
        return f"{self.storage_type}.{self.id}"

    def to_properties(self) -> dict:
        raise NotImplementedError

    def new_storage_file(self, key: str, tier_info=None):
        raise NotImplementedError

    def copy_file(self, path: str, attributes: dict, fn: ProgressFn = None):
        """Upload a local file; returns (key, size)."""
        raise NotImplementedError

    def download_file(self, file_name: str, key: str, fn: ProgressFn = None) -> int:
        raise NotImplementedError

    def delete_file(self, key: str) -> None:
        raise NotImplementedError

    def list_keys(self) -> list[dict]:
        """[{"key": str, "mtime": float | None}] of every stored object
        — the orphan sweep's inventory side. mtime None means the
        backend cannot date the object (the sweep then requires an
        explicit grace_s=0 to touch it)."""
        raise NotImplementedError


class _LifecycleCharge:
    """Charge bulk tier transfer bytes through the shared
    MaintenanceBudget's lifecycle band (ISSUE 17 satellite, carried from
    PR 14): raw-.dat tier_upload/tier_download moves pace at the budget
    rate and yield under overload pressure exactly like EC shard offload,
    instead of bursting past the planes' shaper. Progress callbacks
    report CUMULATIVE done bytes, so the wrapper charges deltas as the
    copy proceeds (spreading the transfer, not pre-bursting one lump);
    `settle` charges whatever a coarse backend never reported. The
    caller's own fn still sees the original (done, pct) stream."""

    def __init__(self, fn: ProgressFn):
        from .maintenance import plane_bucket

        self._bucket = plane_bucket("lifecycle")
        self._fn = fn
        self._last = 0

    def __call__(self, done: int, pct: float) -> None:
        if self._bucket is not None:
            delta = done - self._last
            if delta > 0:
                self._last = done
                self._bucket.consume(delta)
        if self._fn is not None:
            self._fn(done, pct)

    def settle(self, total: int) -> None:
        if self._bucket is not None and total > self._last:
            self._bucket.consume(total - self._last)
            self._last = total


def _progress_copy(src, dst, total: int, fn: ProgressFn) -> int:
    done = 0
    while True:
        chunk = src.read(_COPY_CHUNK)
        if not chunk:
            break
        dst.write(chunk)
        done += len(chunk)
        if fn is not None:
            fn(done, 100.0 * done / total if total else 100.0)
    return done


class LocalTierBackend(BackendStorage):
    """Directory-backed 'remote' store (offline tier)."""

    storage_type = "local"

    def __init__(self, backend_id: str, directory: str):
        super().__init__(backend_id)
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)

    def to_properties(self) -> dict:
        return {"directory": self.directory}

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, key.lstrip("/"))

    def new_storage_file(self, key: str, tier_info=None):
        from .backend import DiskFile

        return DiskFile(self._path(key), create=False, read_only=True)

    def copy_file(self, path: str, attributes: dict, fn: ProgressFn = None):
        key = _tier_key(attributes, path)
        dest = self._path(key)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        total = os.path.getsize(path)
        with open(path, "rb") as src, open(dest, "wb") as dst:
            done = _progress_copy(src, dst, total, fn)
        return key, done

    def download_file(self, file_name: str, key: str, fn: ProgressFn = None) -> int:
        src_path = self._path(key)
        total = os.path.getsize(src_path)
        with open(src_path, "rb") as src, open(file_name, "wb") as dst:
            return _progress_copy(src, dst, total, fn)

    def delete_file(self, key: str) -> None:
        p = self._path(key)
        if os.path.exists(p):
            os.remove(p)

    def list_keys(self) -> list[dict]:
        out: list[dict] = []
        for dirpath, _dirs, files in os.walk(self.directory):
            for fn in files:
                p = os.path.join(dirpath, fn)
                try:
                    mtime = os.path.getmtime(p)
                except OSError:
                    continue
                out.append(
                    {
                        "key": os.path.relpath(p, self.directory).replace(
                            os.sep, "/"
                        ),
                        "mtime": mtime,
                    }
                )
        return out


class S3File:
    """Read-only BackendStorageFile over S3 ranged GETs
    (ref: s3_backend/s3_backend.go S3BackendStorageFile.ReadAt)."""

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        key: str,
        known_size: Optional[int] = None,
    ):
        self._url = f"{endpoint.rstrip('/')}/{bucket}/{key.lstrip('/')}"
        self._size: Optional[int] = known_size

    @property
    def name(self) -> str:
        return self._url

    def read_at(self, size: int, offset: int) -> bytes:
        def attempt(timeout: float) -> bytes:
            _consult_remote_faults("GET", self._url, timeout)
            req = urllib.request.Request(
                self._url,
                headers={"Range": f"bytes={offset}-{offset + size - 1}"},
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                data = resp.read()
                if resp.status == 206:
                    return data
                # 200: the endpoint ignored Range and sent the whole
                # object — slice out the requested window instead of
                # handing back the full body as if it started at offset
                return data[offset : offset + size]

        try:
            return _sync_retry(
                attempt, "tier_s3_read", _READ_DEADLINE_S
            )
        except urllib.error.HTTPError as e:
            if e.code == 416:
                return b""
            raise

    def write_at(self, data: bytes, offset: int) -> int:
        raise OSError("remote tier file is read-only")

    def truncate(self, size: int) -> None:
        raise OSError("remote tier file is read-only")

    def sync(self) -> None:
        pass

    def size(self) -> int:
        if self._size is None:
            def attempt(timeout: float) -> int:
                _consult_remote_faults("HEAD", self._url, timeout)
                req = urllib.request.Request(self._url, method="HEAD")
                with urllib.request.urlopen(req, timeout=timeout) as resp:
                    return int(resp.headers.get("Content-Length", 0))

            self._size = _sync_retry(
                attempt, "tier_s3_head", _READ_DEADLINE_S
            )
        return self._size

    def close(self) -> None:
        pass


class S3Backend(BackendStorage):
    storage_type = "s3"

    def __init__(self, backend_id: str, endpoint: str, bucket: str):
        super().__init__(backend_id)
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket

    def to_properties(self) -> dict:
        return {"endpoint": self.endpoint, "bucket": self.bucket}

    def _url(self, key: str) -> str:
        return f"{self.endpoint}/{self.bucket}/{key.lstrip('/')}"

    def new_storage_file(self, key: str, tier_info=None):
        # the .vif records the remote file's size; using it avoids a
        # blocking HEAD on every heartbeat size collection
        known_size = None
        if tier_info is not None and getattr(tier_info, "files", None):
            known_size = tier_info.files[0].file_size or None
        return S3File(self.endpoint, self.bucket, key, known_size)

    def copy_file(self, path: str, attributes: dict, fn: ProgressFn = None):
        import mmap

        key = _tier_key(attributes, path)
        total = os.path.getsize(path)
        # mmap, not read(): sealed EC shards run to GBs, and a heap copy
        # per upload (x retry attempts, x concurrent offloads) would OOM
        # the volume server this tier exists to relieve — the socket
        # sends straight from page cache, and the buffer is re-readable
        # so _sync_retry's whole-PUT retries need no rewind bookkeeping
        with open(path, "rb") as f:
            buf = (
                mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                if total
                else b""
            )
            try:
                def attempt(timeout: float) -> None:
                    _consult_remote_faults("PUT", self._url(key), timeout)
                    if total:
                        # http.client streams read()-able bodies from
                        # their CURRENT position: an attempt that died
                        # mid-send leaves the mmap advanced, and the
                        # retry would send fewer bytes than its
                        # Content-Length claims — rewind per attempt
                        buf.seek(0)
                    req = urllib.request.Request(
                        self._url(key), data=buf, method="PUT"
                    )
                    # explicit length: urllib would otherwise see the
                    # read()-able body as a stream and switch to
                    # Transfer-Encoding: chunked, where a mid-send
                    # failure's remainder could parse as a COMPLETE
                    # (truncated) object on lenient endpoints
                    req.add_unredirected_header(
                        "Content-Length", str(total)
                    )
                    with urllib.request.urlopen(req, timeout=timeout):
                        pass

                # PUT is idempotent (same bytes, same key): safe to retry
                _sync_retry(attempt, "tier_s3_put", _TRANSFER_DEADLINE_S)
            finally:
                if total:
                    buf.close()
        if fn is not None:
            fn(total, 100.0)
        return key, total

    def download_file(self, file_name: str, key: str, fn: ProgressFn = None) -> int:
        def attempt(timeout: float) -> int:
            _consult_remote_faults("GET", self._url(key), timeout)
            req = urllib.request.Request(self._url(key))
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                total = int(resp.headers.get("Content-Length", 0))
                # (re)open per attempt: a mid-stream failure restarts
                # the download from byte 0 into a truncated file, never
                # appends onto a torn tail
                with open(file_name, "wb") as dst:
                    return _progress_copy(resp, dst, total, fn)

        return _sync_retry(attempt, "tier_s3_get", _TRANSFER_DEADLINE_S)

    def delete_file(self, key: str) -> None:
        def attempt(timeout: float) -> None:
            _consult_remote_faults("DELETE", self._url(key), timeout)
            with urllib.request.urlopen(
                urllib.request.Request(self._url(key), method="DELETE"),
                timeout=timeout,
            ):
                pass

        try:
            _sync_retry(attempt, "tier_s3_delete", _READ_DEADLINE_S)
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise

    def list_keys(self) -> list[dict]:
        """ListObjectsV2 over the bucket (paginated) — works against
        any S3-compatible endpoint including this framework's own
        gateway. LastModified parses to mtime when present; None (the
        minimal blob stand-in has no LIST) surfaces as an error the
        sweep reports instead of guessing."""
        import calendar
        import xml.etree.ElementTree as _ET

        out: list[dict] = []
        token = ""
        while True:
            q = "?list-type=2&max-keys=1000"
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token)
            url = f"{self.endpoint}/{self.bucket}{q}"

            def attempt(timeout: float) -> bytes:
                _consult_remote_faults("GET", url, timeout)
                with urllib.request.urlopen(url, timeout=timeout) as resp:
                    return resp.read()

            body = _sync_retry(attempt, "tier_s3_list", _READ_DEADLINE_S)
            root = _ET.fromstring(body)

            def _local(tag):
                return tag.rsplit("}", 1)[-1]

            truncated = False
            token = ""
            for el in root:
                name = _local(el.tag)
                if name == "Contents":
                    key = mtime = None
                    for sub in el:
                        sn = _local(sub.tag)
                        if sn == "Key":
                            key = sub.text or ""
                        elif sn == "LastModified" and sub.text:
                            # tolerate every common S3 spelling:
                            # fractional seconds, bare 'Z', '+00:00'
                            raw = (
                                sub.text.strip()
                                .split("+")[0]
                                .split(".")[0]
                                .rstrip("Zz")
                            )
                            try:
                                t = time.strptime(
                                    raw, "%Y-%m-%dT%H:%M:%S"
                                )
                                mtime = float(calendar.timegm(t))
                            except ValueError:
                                mtime = None
                    if key:
                        out.append({"key": key, "mtime": mtime})
                elif name == "IsTruncated":
                    truncated = (el.text or "").lower() == "true"
                elif name == "NextContinuationToken":
                    token = el.text or ""
            if not truncated or not token:
                return out


def _tier_key(attributes: dict, path: str) -> str:
    vid = attributes.get("volumeId", "")
    collection = attributes.get("collection", "")
    ext = attributes.get("ext", os.path.splitext(path)[1])
    prefix = f"{collection}_" if collection else ""
    return f"{prefix}{vid}{ext}" if vid else os.path.basename(path)


# ---------------------------------------------------------------------------
# Registry (ref backend.go:42-101)
# ---------------------------------------------------------------------------

BACKEND_STORAGE_FACTORIES: dict[str, Callable[..., BackendStorage]] = {
    "local": lambda bid, props: LocalTierBackend(bid, props["directory"]),
    "s3": lambda bid, props: S3Backend(
        bid, props.get("endpoint", ""), props.get("bucket", "")
    ),
}

BACKEND_STORAGES: dict[str, BackendStorage] = {}


def register_backend(storage: BackendStorage) -> None:
    BACKEND_STORAGES[storage.name] = storage
    if storage.id == "default":
        BACKEND_STORAGES[storage.storage_type] = storage


def snapshot_backends_payload() -> list[dict]:
    """Wire form of every registered backend, for the master heartbeat
    response (ref master_grpc_server.go sending StorageBackends; the
    volume side re-hydrates via load_from_pb_storage_backends). The
    master snapshots this at start — it, not each volume server's env,
    is the single source of backend truth (ISSUE 15 satellite)."""
    seen: set[int] = set()
    out: list[dict] = []
    for storage in BACKEND_STORAGES.values():
        if id(storage) in seen:
            continue  # the "default" alias points at the same object
        seen.add(id(storage))
        out.append(
            {
                "type": storage.storage_type,
                "id": storage.id,
                "properties": storage.to_properties(),
            }
        )
    return out


def load_from_config(config: dict) -> None:
    """config mirrors the `storage.backend` toml section:
    {"s3": {"default": {"enabled": True, "endpoint": ..., "bucket": ...}},
     "local": {"default": {"enabled": True, "directory": ...}}}
    (ref backend.go LoadConfiguration)."""
    for backend_type, instances in (config or {}).items():
        factory = BACKEND_STORAGE_FACTORIES.get(backend_type)
        if factory is None:
            continue
        for backend_id, props in instances.items():
            if not props.get("enabled", True):
                continue
            register_backend(factory(backend_id, props))


def load_from_pb_storage_backends(storage_backends: list[dict]) -> None:
    """Volume-server side: backends pushed in the master heartbeat response
    (ref backend.go:77-95)."""
    for sb in storage_backends or []:
        factory = BACKEND_STORAGE_FACTORIES.get(sb.get("type", ""))
        if factory is None:
            continue
        register_backend(factory(sb.get("id", "default"), sb.get("properties", {})))


def backend_name_to_type_id(name: str) -> tuple[str, str]:
    if "." in name:
        t, _, i = name.partition(".")
        return t, i
    return name, "default"


def get_backend(name: str) -> Optional[BackendStorage]:
    return BACKEND_STORAGES.get(name)


# ---------------------------------------------------------------------------
# Volume tiering operations (ref volume_tier.go, volume_grpc_tier_upload.go)
# ---------------------------------------------------------------------------


def tier_upload(volume, dest_backend_name: str, fn: ProgressFn = None, keep_local: bool = False):
    """Move a volume's .dat to a remote backend; rewrites the .vif so future
    loads read through the tier (ref VolumeTierMoveDatToRemote)."""
    from .volume_info import RemoteFile, VolumeInfo, save_volume_info

    storage = get_backend(dest_backend_name)
    if storage is None:
        raise ValueError(
            f"destination {dest_backend_name} not found,"
            f" supported: {sorted(BACKEND_STORAGES)}"
        )
    backend_type, backend_id = backend_name_to_type_id(dest_backend_name)
    info = volume.volume_info or VolumeInfo(version=volume.version)
    for rf in info.files:
        if rf.backend_type == backend_type and rf.backend_id == backend_id:
            raise ValueError(f"destination {dest_backend_name} already exists")

    dat_path = volume.file_name() + ".dat"
    attributes = {
        "volumeId": str(volume.id),
        "collection": volume.collection,
        "ext": ".dat",
    }
    charge = _LifecycleCharge(fn)
    key, size = storage.copy_file(dat_path, attributes, charge)
    charge.settle(size)
    info.files.append(
        RemoteFile(
            backend_type=backend_type,
            backend_id=backend_id,
            key=key,
            file_size=size,
            modified_time=int(time.time()),
            extension=".dat",
        )
    )
    info.version = volume.version
    # swap the live backend under the volume lock: concurrent reads hold it
    # during pread (ref VolumeTierMoveDatToRemote swaps after copy completes)
    with volume._lock:
        volume.volume_info = info
        save_volume_info(volume.file_name() + ".vif", info)
        volume.load_remote_file()
        volume.no_write_or_delete = True
        if not keep_local:
            os.remove(dat_path)
    return key, size


def tier_download(volume, fn: ProgressFn = None):
    """Bring a tiered volume's .dat back to local disk and drop the remote
    file entry (ref VolumeTierMoveDatFromRemote)."""
    from .backend import DiskFile
    from .volume_info import VolumeInfo, save_volume_info

    name_key = volume.remote_storage_name_key()
    if name_key is None:
        raise ValueError(f"volume {volume.id} is already on local disk")
    storage_name, key = name_key
    storage = get_backend(storage_name)
    if storage is None:
        raise ValueError(
            f"remote storage {storage_name} not found,"
            f" supported: {sorted(BACKEND_STORAGES)}"
        )
    dat_path = volume.file_name() + ".dat"
    charge = _LifecycleCharge(fn)
    size = storage.download_file(dat_path, key, charge)
    charge.settle(size)
    with volume._lock:
        volume.data_backend.close()
        volume.data_backend = DiskFile(dat_path, create=False)
        volume.volume_info = VolumeInfo(version=volume.version)
        save_volume_info(volume.file_name() + ".vif", volume.volume_info)
        volume.has_remote_file = False
        volume.no_write_or_delete = False
    storage.delete_file(key)
    return size
