"""Volume engine: one append-only .dat + .idx pair.

Semantics follow the reference volume (ref: weed/storage/volume.go:21-47,
volume_read_write.go, volume_loading.go, volume_checking.go):

- writes append a v3 needle record and log (key, offset, size) to the .idx;
- deletes append a zero-data tombstone needle and log TOMBSTONE_FILE_SIZE;
- reads look up the in-memory map and pread one record, verifying cookie at a
  higher layer and TTL expiry here;
- load replays the .idx and verifies the last entry against the .dat (CRC),
  marking the volume read-only on failure.

The reference's async group-commit worker (volume_read_write.go:290-363)
batches fsyncs across goroutines; here a single lock serializes writers and
`sync=True` requests fsync with the same truncate-rollback-on-failure
guarantee.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..types import (
    MAX_POSSIBLE_VOLUME_SIZE,
    NEEDLE_HEADER_SIZE,
    NEEDLE_MAP_ENTRY_SIZE,
    NEEDLE_PADDING_SIZE,
    TOMBSTONE_FILE_SIZE,
    to_actual_offset,
    to_offset_units,
)
from .backend import BackendStorageFile, DiskFile
from .needle import (
    Needle,
    get_actual_size,
    needle_body_length,
    read_needle_data,
    read_needle_header,
)
from .needle_map import NeedleMap, load_needle_map, new_needle_map
from .super_block import SUPER_BLOCK_SIZE, SuperBlock, read_super_block
from .ttl import EMPTY_TTL


_DEVICE_OK: Optional[bool] = None


def _device_available() -> bool:
    """True when jax can run the bulk-lookup program (any backend)."""
    global _DEVICE_OK
    if _DEVICE_OK is None:
        try:
            import jax

            jax.devices()
            _DEVICE_OK = True
        except Exception:
            _DEVICE_OK = False
    return _DEVICE_OK


class NotFound(Exception):
    pass


class AlreadyDeleted(Exception):
    pass


class VolumeSizeExceeded(Exception):
    pass


class CookieMismatch(Exception):
    pass


def volume_base_name(directory: str, collection: str, vid: int) -> str:
    """Ref: weed/storage/volume.go FileName() — dir/[collection_]vid."""
    if collection:
        return os.path.join(directory, f"{collection}_{vid}")
    return os.path.join(directory, str(vid))


def check_volume_data_integrity(
    dat: BackendStorageFile, version: int, idx_path: str
) -> int:
    """Verify idx size alignment and the last entry's needle CRC; returns
    last_append_at_ns (ref: weed/storage/volume_checking.go:15-46)."""
    idx_size = os.path.getsize(idx_path)
    if idx_size % NEEDLE_MAP_ENTRY_SIZE != 0:
        raise ValueError(f"index file size {idx_size} not a multiple of 16")
    if idx_size == 0:
        return 0
    from .idx import parse_entry

    with open(idx_path, "rb") as f:
        f.seek(idx_size - NEEDLE_MAP_ENTRY_SIZE)
        key, offset_units, size = parse_entry(f.read(NEEDLE_MAP_ENTRY_SIZE))
    if offset_units == 0:
        return 0
    if size == TOMBSTONE_FILE_SIZE:
        size = 0
    n = read_needle_data(dat, to_actual_offset(offset_units), size, version)
    if n.id != key:
        raise ValueError(f"index key {key:#x} does not match needle id {n.id:#x}")
    return n.append_at_ns


class UnrecoverableCorruption(Exception):
    """A COMPLETE record failed verification: bit rot, not a torn tail.
    Truncating would destroy an acked, durable write — the volume must go
    read-only with the evidence intact instead."""


def _idx_entry_status(
    dat: BackendStorageFile, version: int, key: int, offset_units: int,
    size: int, dat_size: int,
) -> tuple[str, Optional[int]]:
    """Classify the record one .idx entry references:
    ("ok", end)      — complete and CRC-valid;
    ("ok-weak", None) — delete-of-absent-key entry (offset 0): valid but
                        names no position;
    ("torn", None)   — the record extends past EOF: a crash artifact,
                        safe to drop (its write was never acked);
    ("corrupt", None) — complete on disk but fails id/size/CRC checks:
                        bit rot, NOT recoverable by truncation."""
    if offset_units == 0:
        return ("ok-weak", None)
    body_size = 0 if size == TOMBSTONE_FILE_SIZE else size
    offset = to_actual_offset(offset_units)
    end = offset + get_actual_size(body_size, version)
    if end > dat_size:
        return ("torn", None)
    try:
        n = read_needle_data(dat, offset, body_size, version)
    except Exception:
        return ("corrupt", None)
    if n.id != key:
        return ("corrupt", None)
    return ("ok", end)


def expected_dat_frontier(
    version: int, idx_path: str, data_start: int
) -> Optional[int]:
    """Where the .dat should end according to the .idx: the MAX record end
    over every entry (every append logs exactly one entry after its record
    lands). Order-independent on purpose — `weed-tpu fix` and vacuum
    rebuild key-SORTED index files, where the last entry is the largest
    key, not the latest append. None when the frontier cannot be derived
    (torn idx, no positional entries). Vectorized: this runs on every
    memory-kind volume load."""
    idx_size = os.path.getsize(idx_path)
    if idx_size % NEEDLE_MAP_ENTRY_SIZE != 0:
        return None
    if idx_size == 0:
        return data_start
    import numpy as np

    from ..types import VERSION3
    from .idx import parse_index_bytes

    with open(idx_path, "rb") as f:
        _keys, offsets, sizes = parse_index_bytes(f.read())
    live = offsets > 0
    if not live.any():
        return None
    body = np.where(
        sizes == np.uint32(TOMBSTONE_FILE_SIZE), 0, sizes
    ).astype(np.int64)
    # get_actual_size, vectorized: header+body+crc(+ts), padded to 8 with
    # 1..8 bytes (8 - base%8 is already in 1..8, matching padding_length)
    base = NEEDLE_HEADER_SIZE + body + 4 + (8 if version == VERSION3 else 0)
    ends = offsets.astype(np.int64) * NEEDLE_PADDING_SIZE + base + (
        8 - base % 8
    )
    return int(ends[live].max())


def recover_torn_tail(
    dat: BackendStorageFile, version: int, idx_path: str,
    data_start: int = SUPER_BLOCK_SIZE,
) -> dict:
    """Bring a volume whose process died mid-append back to a consistent
    prefix (the reference instead marks the volume read-only,
    volume_loading.go:100-116 — we repair).

    Verifies every .idx entry against its record (complete + CRC-valid).
    Torn entries — records running past EOF, the shape a crash or a
    power-loss-reordered flush leaves — must form a contiguous tail,
    which is truncated away (their writes were never acked). The .dat is
    then scanned FORWARD from the highest verified record end (order-
    independent: fix/vacuum write key-sorted index files) to re-index
    fully-written records whose index entry was lost (crash between the
    .dat append and the .idx append), and truncated at the first
    incomplete record. Any COMPLETE record failing verification is bit
    rot, not a crash artifact: UnrecoverableCorruption, volume goes
    read-only. Returns counts for the degraded-mode metrics:
    {records_recovered, dat_bytes_dropped, idx_entries_dropped,
    idx_bytes_torn}.
    """
    from .idx import entry_to_bytes, iter_index

    stats = {
        "records_recovered": 0,
        "dat_bytes_dropped": 0,
        "idx_entries_dropped": 0,
        "idx_bytes_torn": 0,
    }
    idx_size = os.path.getsize(idx_path)
    torn = idx_size % NEEDLE_MAP_ENTRY_SIZE
    if torn:
        idx_size -= torn
        os.truncate(idx_path, idx_size)
        stats["idx_bytes_torn"] = torn
    dat_size = dat.size()
    n_entries = idx_size // NEEDLE_MAP_ENTRY_SIZE
    max_valid_end = min(data_start, dat_size)
    first_torn: Optional[int] = None
    with open(idx_path, "rb") as f:
        for i, (key, offset_units, size) in enumerate(iter_index(f)):
            status, end = _idx_entry_status(
                dat, version, key, offset_units, size, dat_size
            )
            if status == "corrupt":
                raise UnrecoverableCorruption(
                    f"record for key {key:#x} is complete but invalid "
                    f"(bit rot); refusing to truncate acked data"
                )
            if status == "torn":
                if first_torn is None:
                    first_torn = i
                continue
            if first_torn is not None:
                # a verified entry AFTER a torn one is not the contiguous
                # tail a crash leaves — too strange to repair blindly
                raise UnrecoverableCorruption(
                    "valid index entry follows a torn one; "
                    "not a crash-shaped tail"
                )
            if end is not None:  # positional entry ("ok-weak" has no end)
                max_valid_end = max(max_valid_end, end)
    if first_torn is not None:
        os.truncate(idx_path, first_torn * NEEDLE_MAP_ENTRY_SIZE)
        stats["idx_entries_dropped"] = n_entries - first_torn
    pos = max_valid_end
    recovered: list[bytes] = []
    while pos + NEEDLE_HEADER_SIZE <= dat_size:
        try:
            header, body_len = read_needle_header(dat, version, pos)
        except Exception:
            break
        if header.id == 0 and header.size == 0:
            break  # zero-fill, never a real record
        total = NEEDLE_HEADER_SIZE + body_len
        if pos + total > dat_size:
            break  # torn mid-record: never acked, drop it
        try:
            n = Needle()
            n.read_bytes(dat.read_at(total, pos), pos, header.size, version)
        except Exception:
            break
        size_for_index = (
            n.size if len(n.data) else TOMBSTONE_FILE_SIZE
        )  # empty record == tombstone append (volume_read_write.go:186)
        recovered.append(
            entry_to_bytes(n.id, to_offset_units(pos), size_for_index)
        )
        pos += total
    if recovered:
        with open(idx_path, "ab") as f:
            f.write(b"".join(recovered))
        stats["records_recovered"] = len(recovered)
    if pos < dat_size:
        dat.truncate(pos)
        stats["dat_bytes_dropped"] = dat_size - pos
    return stats


def digest_fold(keys, sizes) -> int:
    """XOR-fold of splitmix64-mixed (key, size) terms over live index
    columns — the commutative content digest replicas compare. Pure
    integer arithmetic (never Python hash(): that is salted per process,
    and replicas live in different processes)."""
    import numpy as np

    if len(keys) == 0:
        return 0
    x = np.asarray(keys, dtype=np.uint64) ^ (
        np.asarray(sizes, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    )
    x ^= x >> np.uint64(30)
    x *= np.uint64(0xBF58476D1CE4E5B9)
    x ^= x >> np.uint64(27)
    x *= np.uint64(0x94D049BB133111EB)
    x ^= x >> np.uint64(31)
    return int(np.bitwise_xor.reduce(x))


class Volume:
    def __init__(
        self,
        directory: str,
        collection: str,
        vid: int,
        replica_placement=None,
        ttl=None,
        create: bool = True,
        needle_map_kind: str = "memory",
    ):
        self.dir = directory
        self.collection = collection
        self.id = vid
        self.no_write_or_delete = False
        self.is_compacting = False
        self.last_append_at_ns = 0
        self.last_modified_ts_seconds = 0
        self.last_compact_index_offset = 0
        self.last_compact_revision = 0
        self._lock = threading.RLock()
        # anti-entropy state: memoized content digest (keyed by the needle
        # map's mutation token) + the scrub quarantine flag heartbeats carry
        self._digest_cache: Optional[tuple] = None
        self.scrub_corrupt = False
        # lifecycle plane: decayed read/write heat, restored from the
        # sidecar so a clean restart keeps the volume's temperature
        from .heat import HeatTracker

        self.heat = HeatTracker.load(
            volume_base_name(directory, collection, vid) + ".heat"
        )
        # device-resident index snapshot for bulk probes, keyed by the
        # map's mutation token (see bulk_lookup)
        from ..ops.snapshot_cache import SnapshotCache

        self._index_cache = SnapshotCache()

        base = self.file_name()
        # a dead compaction's shadow files must be repaired BEFORE anything
        # opens the .dat/.idx: sweep .cpd/.cpx leftovers, or complete a
        # commit that crashed between its two renames (vacuum.py)
        try:
            from .vacuum import sweep_compaction_shadows

            swept = sweep_compaction_shadows(base)
            if swept:
                from ..util.log import warning

                warning(
                    "volume %d: %s stale compaction shadows at load",
                    vid, swept,
                )
        except OSError:
            pass  # unreadable shadows: the load below decides read-only
        dat_exists = os.path.exists(base + ".dat")

        # tiered volumes have no local .dat; their .vif names the remote
        # copy (ref volume_tier.go maybeLoadVolumeInfo/LoadRemoteFile)
        self.volume_info = None
        self.has_remote_file = False
        self._maybe_load_volume_info()

        if self.has_remote_file:
            self.no_write_or_delete = True
            self.data_backend: BackendStorageFile = None  # set below
            self.load_remote_file()
            self.super_block = read_super_block(self.data_backend)
            self.needle_map_kind = needle_map_kind
            self.nm = self._open_needle_map(base, needle_map_kind)
            return

        if not dat_exists and not create:
            raise FileNotFoundError(f"Volume data file {base}.dat does not exist")

        self.data_backend: BackendStorageFile = DiskFile(base + ".dat", create=True)
        if dat_exists and self.data_backend.size() >= SUPER_BLOCK_SIZE:
            self.super_block = read_super_block(self.data_backend)
        else:
            from .super_block import ReplicaPlacement

            self.super_block = SuperBlock(
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl or EMPTY_TTL,
            )
            self.data_backend.write_at(self.super_block.to_bytes(), 0)

        self.needle_map_kind = needle_map_kind
        self.recovery_stats: Optional[dict] = None
        self.nm: NeedleMap
        if os.path.exists(base + ".idx") and dat_exists:
            try:
                self.last_append_at_ns = check_volume_data_integrity(
                    self.data_backend, self.version, base + ".idx"
                )
                if needle_map_kind == "memory":
                    # the last idx entry can verify while the dat still
                    # carries a torn record PAST it (crash mid-append,
                    # before the idx entry landed) — check the frontier
                    expected = expected_dat_frontier(
                        self.version, base + ".idx",
                        self.super_block.block_size(),
                    )
                    if expected is not None and expected != self.data_backend.size():
                        self._recover_torn_tail(base)
            except Exception:
                # the tail is torn (crash mid-append). The reference mounts
                # read-only; we repair to the last CRC-valid needle
                # boundary — but only for the log-format .idx the memory
                # and lsm maps replay (sqlite/sorted have other formats)
                if needle_map_kind in ("memory", "lsm"):
                    self._recover_torn_tail(base)
                else:
                    self.no_write_or_delete = True
            self.nm = self._open_needle_map(base, needle_map_kind)
            if needle_map_kind == "lsm":
                # same torn-record-past-the-frontier check as "memory",
                # but from the map's own running maximum — the whole
                # point of the snapshot mount is NOT re-reading the .idx
                expected = self.nm.expected_dat_frontier(
                    self.super_block.block_size()
                )
                if expected is not None and expected != self.data_backend.size():
                    self.nm.close()
                    self._recover_torn_tail(base)
                    # recovery may have truncated/appended the log; the
                    # reopen revalidates the snapshot binding against it
                    self.nm = self._open_needle_map(base, needle_map_kind)
            if needle_map_kind == "sorted":
                # sorted-file maps can't Put; the reference only uses them
                # on read-only volume loads (ref volume_loading.go:68-95)
                self.no_write_or_delete = True
        else:
            if needle_map_kind == "leveldb":
                from .needle_map.disk_maps import SqliteNeedleMap

                if os.path.exists(base + ".idx"):
                    os.truncate(base + ".idx", 0)
                self.nm = SqliteNeedleMap(base + ".idx")
            elif needle_map_kind == "lsm":
                from .needle_map.lsm_map import new_lsm_needle_map

                self.nm = new_lsm_needle_map(
                    base + ".idx", version=self.version
                )
            else:
                # "sorted" can't index a fresh writable volume; fall back
                # to the in-memory map until a read-only reload
                self.nm = new_needle_map(base + ".idx")

    def _open_needle_map(self, base: str, kind: str):
        """Mapper selection (ref NeedleMapKind, weed/storage/needle_map.go:14-19):
        memory=CompactMap replay, leveldb=disk B-tree, sorted=read-only
        .sdx, lsm=memory-bounded out-of-core map with snapshot mount."""
        if kind == "leveldb":
            from .needle_map.disk_maps import SqliteNeedleMap

            return SqliteNeedleMap(base + ".idx")
        if kind == "sorted":
            from .needle_map.disk_maps import SortedFileNeedleMap

            return SortedFileNeedleMap(base + ".idx")
        if kind == "lsm":
            from .needle_map.lsm_map import load_lsm_needle_map

            return load_lsm_needle_map(base + ".idx", version=self.version)
        return load_needle_map(base + ".idx")

    def _recover_torn_tail(self, base: str) -> None:
        """Repair a torn .dat/.idx tail on load; read-only fallback when
        even the repaired prefix fails verification."""
        from ..util.log import warning
        from ..util.metrics import TORN_TAIL_COUNTER

        try:
            stats = recover_torn_tail(
                self.data_backend, self.version, base + ".idx",
                data_start=self.super_block.block_size(),
            )
            self.last_append_at_ns = check_volume_data_integrity(
                self.data_backend, self.version, base + ".idx"
            )
        except Exception:
            self.no_write_or_delete = True
            return
        self.recovery_stats = stats
        TORN_TAIL_COUNTER.inc(item="volumes")
        for item, key in (
            ("records_recovered", "records_recovered"),
            ("dat_bytes_dropped", "dat_bytes_dropped"),
            ("idx_entries_dropped", "idx_entries_dropped"),
        ):
            if stats[key]:
                TORN_TAIL_COUNTER.inc(stats[key], item=item)
        warning(
            "volume %d: torn tail recovered (%d records re-indexed, "
            "%d dat bytes dropped, %d idx entries dropped)",
            self.id, stats["records_recovered"], stats["dat_bytes_dropped"],
            stats["idx_entries_dropped"],
        )

    # --- basic accessors ---
    def file_name(self) -> str:
        return volume_base_name(self.dir, self.collection, self.id)

    # --- tiering (ref volume_tier.go) ---
    def _maybe_load_volume_info(self) -> None:
        from .volume_info import load_volume_info

        info = load_volume_info(self.file_name() + ".vif")
        if info is not None:
            self.volume_info = info
            self.has_remote_file = bool(info.files)

    def remote_storage_name_key(self):
        """-> (backend_name, key) of the tiered .dat, or None."""
        if self.volume_info is None or not self.volume_info.files:
            return None
        rf = self.volume_info.files[0]
        return f"{rf.backend_type}.{rf.backend_id}", rf.key

    def load_remote_file(self) -> None:
        """Point data_backend at the remote copy (ref LoadRemoteFile)."""
        from .tier_backend import get_backend

        name, key = self.remote_storage_name_key()
        storage = get_backend(name)
        if storage is None:
            raise ValueError(f"backend storage {name} not configured")
        if self.data_backend is not None:
            self.data_backend.close()
        self.data_backend = storage.new_storage_file(key, self.volume_info)
        self.has_remote_file = True

    @property
    def version(self) -> int:
        return self.super_block.version

    @property
    def ttl(self):
        return self.super_block.ttl

    def content_size(self) -> int:
        return self.nm.content_size

    def deleted_size(self) -> int:
        return self.nm.deleted_size

    def file_count(self) -> int:
        return self.nm.file_count

    def deleted_count(self) -> int:
        return self.nm.deleted_count

    def max_file_key(self) -> int:
        return self.nm.max_file_key

    def index_file_size(self) -> int:
        return self.nm.index_file_size()

    def data_file_size(self) -> int:
        return self.data_backend.size()

    def is_read_only(self) -> bool:
        return self.no_write_or_delete

    def content_digest(self) -> int:
        """Order-independent 64-bit digest of the LIVE content set — the
        XOR-fold of a mixed (key, size) term per non-deleted needle. Two
        replicas holding the same needles report the same digest no matter
        how their appends interleaved on disk, so the master can compare
        digests straight off heartbeats to catch diverged/stale replicas
        (the anti-entropy plane's cheap invariant). Memoized on the needle
        map's mutation token: steady state costs a token compare."""
        with self._lock:
            try:
                token = self.nm.snapshot_token()
            except Exception:
                token = None
            cached = self._digest_cache
            if token is not None and cached is not None and cached[0] == token:
                return cached[1]
            try:
                keys, _offsets, sizes = self.nm.snapshot()
            except Exception:
                return 0
            d = digest_fold(keys, sizes)
            if token is not None:
                self._digest_cache = (token, d)
            return d

    def quarantine(self, reason: str) -> None:
        """Scrub found latent damage: freeze writes and flag the volume for
        the master's repair scheduler. NEVER deletes anything — the
        evidence stays on disk for repair/forensics."""
        from ..util.log import warning

        self.no_write_or_delete = True
        self.scrub_corrupt = True
        warning("volume %d quarantined: %s", self.id, reason)

    def garbage_level(self) -> float:
        """Ref: volume_vacuum.go:20-34."""
        if self.content_size() == 0:
            return 0.0
        return self.deleted_size() / self.content_size()

    # --- data path ---
    def _is_file_unchanged(self, n: Needle) -> bool:
        """Dedup identical rewrite (ref: volume_read_write.go:22-41)."""
        if str(self.ttl):
            return False
        nv = self.nm.get(n.id)
        if nv is None or nv.offset_units == 0 or nv.size == TOMBSTONE_FILE_SIZE:
            return False
        try:
            old = read_needle_data(
                self.data_backend, to_actual_offset(nv.offset_units), nv.size, self.version
            )
        except Exception:
            return False
        return old.cookie == n.cookie and old.data == n.data

    def can_accept(self, data_len: int) -> bool:
        """Deterministic append preconditions (writable + under the
        offset-addressable size ceiling) — callers that pipeline side
        effects (replica fan-out) check these BEFORE launching them, so a
        write that is guaranteed to fail locally never lands data
        elsewhere. Advisory: the append itself re-checks under the lock."""
        if self.no_write_or_delete:
            return False
        return (
            self.content_size() + get_actual_size(data_len, self.version)
            <= MAX_POSSIBLE_VOLUME_SIZE
        )

    def write_needle(self, n: Needle, sync: bool = False) -> tuple[int, int, bool]:
        """Append a needle; returns (offset, size, is_unchanged)
        (ref: volume_read_write.go:71-142)."""
        if self.no_write_or_delete:
            raise PermissionError(f"volume {self.id} is read only")
        if n.ttl is None or n.ttl == EMPTY_TTL:
            if self.ttl != EMPTY_TTL:
                n.set_ttl(self.ttl)
        with self._lock:
            actual_size = get_actual_size(len(n.data), self.version)
            if MAX_POSSIBLE_VOLUME_SIZE < self.content_size() + actual_size:
                raise VolumeSizeExceeded(
                    f"volume size limit {MAX_POSSIBLE_VOLUME_SIZE} exceeded! "
                    f"current size is {self.content_size()}"
                )
            if self._is_file_unchanged(n):
                return 0, len(n.data), True

            nv = self.nm.get(n.id)
            if nv is not None and nv.offset_units != 0:
                existing, _ = read_needle_header(
                    self.data_backend, self.version, to_actual_offset(nv.offset_units)
                )
                if existing.cookie != n.cookie:
                    raise CookieMismatch(f"mismatching cookie {n.cookie:x}")

            self.heat.note_write()
            n.append_at_ns = time.time_ns()
            end = self.data_backend.size()
            blob, size_for_index, _ = n.to_bytes(self.version)
            try:
                self.data_backend.write_at(blob, end)
                if sync:
                    self.data_backend.sync()
            except Exception:
                self.data_backend.truncate(end)
                raise
            self.last_append_at_ns = n.append_at_ns
            offset = end

            if nv is None or to_actual_offset(nv.offset_units) < offset:
                self.nm.put(n.id, to_offset_units(offset), n.size)
            if self.last_modified_ts_seconds < n.last_modified:
                self.last_modified_ts_seconds = n.last_modified
            return offset, size_for_index, False

    def write_needle_batch(self, needles: list) -> list:
        """Append MANY needles as ONE coalesced .dat extent + ONE .idx
        extent (the multi-needle append satellite): a `!batch/put` frame
        of N needles costs two pwrites total instead of 2N — the ~265µs
        two-syscall floor per needle was the 1M-key soak's write cap.

        Per-needle semantics match write_needle exactly (TTL inherit,
        size ceiling, unchanged-dedup, cookie check); a needle failing
        its OWN precondition reports an Exception in its result slot
        while the rest of the batch proceeds. The coalesced extent write
        is all-or-nothing: on failure the .dat truncates back and every
        pending slot fails. Returns one (offset, size_for_index,
        is_unchanged) tuple or Exception per input needle, in order."""
        if self.no_write_or_delete:
            raise PermissionError(f"volume {self.id} is read only")
        results: list = [None] * len(needles)
        with self._lock:
            start = self.data_backend.size()
            parts: list = []
            entries: list = []  # (key, offset_units, size) for put_batch
            pending: list = []  # (i, needle, offset, size_for_index)
            accrued = 0
            for i, n in enumerate(needles):
                try:
                    if n.ttl is None or n.ttl == EMPTY_TTL:
                        if self.ttl != EMPTY_TTL:
                            n.set_ttl(self.ttl)
                    actual_size = get_actual_size(len(n.data), self.version)
                    if (
                        MAX_POSSIBLE_VOLUME_SIZE
                        < self.content_size() + accrued + actual_size
                    ):
                        raise VolumeSizeExceeded(
                            f"volume size limit {MAX_POSSIBLE_VOLUME_SIZE} "
                            f"exceeded! current size is {self.content_size()}"
                        )
                    if self._is_file_unchanged(n):
                        results[i] = (0, len(n.data), True)
                        continue
                    nv = self.nm.get(n.id)
                    if nv is not None and nv.offset_units != 0:
                        existing, _ = read_needle_header(
                            self.data_backend, self.version,
                            to_actual_offset(nv.offset_units),
                        )
                        if existing.cookie != n.cookie:
                            raise CookieMismatch(
                                f"mismatching cookie {n.cookie:x}"
                            )
                    n.append_at_ns = time.time_ns()
                    offset = start + accrued
                    blob, size_for_index, _ = n.to_bytes(self.version)
                    parts.append(blob)
                    entries.append(
                        (n.id, to_offset_units(offset), n.size)
                    )
                    pending.append((i, n, offset, size_for_index))
                    accrued += len(blob)
                except Exception as e:
                    results[i] = e
            if not pending:
                return results
            self.heat.note_write(len(pending))
            try:
                self.data_backend.write_at(b"".join(parts), start)
            except Exception as e:
                try:
                    self.data_backend.truncate(start)
                except Exception:
                    pass
                for i, _n, _off, _sfi in pending:
                    results[i] = e
                return results
            put_batch = getattr(self.nm, "put_batch", None)
            if put_batch is not None:
                put_batch(entries)
            else:  # sorted-file maps can't batch; mirror the loop
                for key, off_units, size in entries:
                    self.nm.put(key, off_units, size)
            for i, n, offset, size_for_index in pending:
                self.last_append_at_ns = n.append_at_ns
                if self.last_modified_ts_seconds < n.last_modified:
                    self.last_modified_ts_seconds = n.last_modified
                results[i] = (offset, size_for_index, False)
            return results

    def delete_needle(self, n: Needle) -> int:
        """Append tombstone + mark map; returns freed size
        (ref: volume_read_write.go:186-231)."""
        if self.no_write_or_delete:
            raise PermissionError(f"volume {self.id} is read only")
        with self._lock:
            nv = self.nm.get(n.id)
            if nv is None or nv.size == TOMBSTONE_FILE_SIZE:
                return 0
            self.heat.note_write()
            size = nv.size
            n.data = b""
            n.append_at_ns = time.time_ns()
            end = self.data_backend.size()
            blob, _, _ = n.to_bytes(self.version)
            self.data_backend.write_at(blob, end)
            self.last_append_at_ns = n.append_at_ns
            self.nm.delete(n.id, to_offset_units(end))
            return size

    def read_needle(self, n: Needle) -> int:
        """Fill in needle content by map lookup; returns bytes read
        (ref: volume_read_write.go:255-288)."""
        got = self.read_needle_by_key(n.id)
        if got is not n:
            n.__dict__.update(got.__dict__)
        return len(n.data)

    def read_needle_by_key(self, key: int) -> Needle:
        """Serving fast-path read: map lookup + pread + parse in one step,
        returning the hydrated needle directly. Same semantics as
        read_needle without the caller-allocated shell needle and the
        per-field dict merge (both measurable at read-QPS rates)."""
        return self.read_needle_by_key_located(key)[0]

    def read_needle_by_key_located(self, key: int) -> tuple[Needle, int, int]:
        """read_needle_by_key plus the (offset_units, size) the record was
        served from. The location is the hot-needle cache's validity
        token: a later hit is legal only while the live map still points
        the key at the same location (append-only .dat ⇒ same location,
        same bytes; any overwrite/delete moves or tombstones the entry)."""
        self.heat.note_read()
        with self._lock:
            nv = self.nm.get(key)
            if nv is None or nv.offset_units == 0:
                raise NotFound(f"needle {key} not found")
            if nv.size == TOMBSTONE_FILE_SIZE:
                raise AlreadyDeleted(f"needle {key} already deleted")
            if nv.size == 0:
                return Needle(id=key), nv.offset_units, 0
            n = read_needle_data(
                self.data_backend, to_actual_offset(nv.offset_units), nv.size, self.version
            )
        if n.has_ttl() and n.ttl is not None and n.ttl.minutes:
            if n.has_last_modified_date() and time.time() >= n.last_modified + n.ttl.minutes * 60:
                raise NotFound(f"needle {key} expired")
        return n, nv.offset_units, nv.size

    def locate_live(self, key: int):
        """(offset_units, size) of the key's live record, or None when the
        key is absent/deleted. One locked map probe — the hot-needle
        cache's per-hit freshness check. Cache hits are real reads: they
        count into the lifecycle heat here (the only per-hit volume
        touchpoint), or a perfectly-cached volume would look COLD to the
        lifecycle planner and get erasure-coded out from under its
        traffic."""
        self.heat.note_read()
        with self._lock:
            nv = self.nm.get(key)
        if (
            nv is None
            or nv.offset_units == 0
            or nv.size == TOMBSTONE_FILE_SIZE
        ):
            return None
        return nv.offset_units, nv.size

    def bulk_lookup(self, keys, use_device: Optional[bool] = None):
        """Batched fid -> (offset, size) index probes.

        This is the TPU read north star: instead of one binary search per
        request (ref: weed/storage/needle_map/compact_map.go:145-172), bulk
        probes run as a single branchless batched binary search over the
        device-resident IndexSnapshot (ops/index_kernel.py). The snapshot is
        cached per volume and invalidated by the map's mutation token, so
        steady-state serving costs no host->device transfer of the table.

        Returns (offset_units u32[P], sizes u32[P], found bool[P]); a probe
        of a deleted or absent needle reports found=False.
        """
        import numpy as _np

        keys = _np.asarray(keys, dtype=_np.uint64)
        snap_fn = getattr(self.nm, "snapshot", None)
        if use_device is None:
            # tiny batches aren't worth a device dispatch (or, on first
            # use, a jit compile) — serve them from the host map. The
            # 5-byte-offset variant stays on the host: its offset units
            # exceed the kernel's u32 columns.
            from ..types import OFFSET_SIZE

            use_device = (
                snap_fn is not None
                and OFFSET_SIZE == 4
                and len(keys) >= 64
                and _device_available()
            )
        if not use_device or snap_fn is None:
            from ..types import OFFSET_SIZE

            # u64 offsets under the 5-byte variant (units exceed u32)
            off_dtype = _np.uint64 if OFFSET_SIZE == 5 else _np.uint32
            offsets = _np.zeros(len(keys), dtype=off_dtype)
            sizes = _np.zeros(len(keys), dtype=_np.uint32)
            found = _np.zeros(len(keys), dtype=bool)
            for i, k in enumerate(keys):
                nv = self.nm.get(int(k))
                if (
                    nv is not None
                    and nv.offset_units != 0
                    and nv.size != TOMBSTONE_FILE_SIZE
                ):
                    offsets[i] = nv.offset_units
                    sizes[i] = nv.size
                    found[i] = True
            return offsets, sizes, found

        def locked_cols():
            with self._lock:  # map mutations happen under the volume lock
                return self.nm.snapshot()

        accel = self._index_cache.get(self.nm.snapshot_token, locked_cols)
        # IndexSnapshot.lookup pads probe batches to power-of-two buckets
        # itself, so variable micro-batch sizes don't each jit-compile
        return accel.lookup(keys)

    def read_needle_at(self, offset_units: int, size: int) -> Needle:
        """pread one record at a known index location, under the volume lock
        and with the same TTL-expiry visibility as read_needle."""
        self.heat.note_read()
        with self._lock:
            n = read_needle_data(
                self.data_backend, to_actual_offset(offset_units), size, self.version
            )
        if n.has_ttl() and n.ttl is not None and n.ttl.minutes:
            if (
                n.has_last_modified_date()
                and time.time() >= n.last_modified + n.ttl.minutes * 60
            ):
                raise NotFound(f"needle {n.id} expired")
        return n

    def sync(self) -> None:
        self.nm.sync()
        self.data_backend.sync()

    def close(self) -> None:
        # persist the temperature: a clean restart must not look like a
        # cold start to the lifecycle planner
        try:
            self.heat.save(self.file_name() + ".heat")
        except Exception:
            pass
        with self._lock:
            self.nm.close()
            self.data_backend.close()

    def destroy(self, keep_ec_files: bool = False) -> None:
        """Remove all files (ref: volume_read_write.go:44-65).

        keep_ec_files spares the sidecars a just-generated EC volume at
        the same base name still needs — the .vif (RS geometry) and the
        .heat temperature — while still destroying the .dat/.idx, so a
        volume retired by EC conversion can never be re-discovered and
        resurrected as a writable normal volume by a later mount scan."""
        self.close()
        base = self.file_name()
        exts = (".dat", ".idx", ".sdx", ".cpd", ".cpx", ".scrub")
        if not keep_ec_files:
            exts += (".vif", ".heat")
        for ext in exts:
            try:
                os.remove(base + ext)
            except FileNotFoundError:
                pass
        # lsm sidecars (snapshot manifest + run files), whatever the
        # CURRENT kind is — a volume once mounted with -index lsm may be
        # destroyed under another kind
        from .needle_map.lsm_map import invalidate_snapshot

        invalidate_snapshot(base)

    # --- scanning ---
    def scan(
        self,
        visit: Callable[[Needle, int, bytes], None],
        read_body: bool = True,
    ) -> None:
        """Visit every record in the .dat in file order
        (ref: volume_read_write.go:371-428)."""
        scan_volume_file(self.data_backend, self.super_block, visit, read_body)


def scan_volume_file(
    dat: BackendStorageFile,
    super_block: SuperBlock,
    visit: Callable[[Needle, int, bytes], None],
    read_body: bool = True,
) -> None:
    version = super_block.version
    offset = super_block.block_size()
    end = dat.size()
    while offset + NEEDLE_HEADER_SIZE <= end:
        try:
            n, body_len = read_needle_header(dat, version, offset)
        except EOFError:
            return
        body = b""
        if read_body and body_len > 0:
            body = dat.read_at(body_len, offset + NEEDLE_HEADER_SIZE)
            n.read_needle_body_bytes(body, version)
        visit(n, offset, body)
        offset += NEEDLE_HEADER_SIZE + body_len
