"""File id = (volume id, needle key, cookie) with the reference string format
``{vid},{key_hex}{cookie_hex8}`` where leading zero bytes of the 12-byte
key+cookie buffer are trimmed (ref: weed/storage/needle/file_id.go:63-73).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..types import (
    COOKIE_SIZE,
    NEEDLE_ID_SIZE,
    cookie_to_bytes,
    needle_id_to_bytes,
)


def parse_volume_id(s: str) -> int:
    """Volume id string -> int; ignores anything after non-digits
    (ref: weed/storage/needle/volume_id.go NewVolumeId uses ParseUint)."""
    return int(s)


def format_needle_id_cookie(key: int, cookie: int) -> str:
    buf = needle_id_to_bytes(key) + cookie_to_bytes(cookie)
    nonzero = 0
    while nonzero < len(buf) - 1 and buf[nonzero] == 0:
        nonzero += 1
    return buf[nonzero:].hex()


def parse_needle_id_cookie(s: str) -> tuple[int, int]:
    """Reverse of format_needle_id_cookie: last 8 hex chars are the cookie."""
    if len(s) <= 8:
        raise ValueError(f"needle id+cookie too short: {s!r}")
    # strip any url-style suffix like ".jpg" the reference tolerates upstream
    key = int(s[:-8], 16)
    cookie = int(s[-8:], 16)
    return key, cookie


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    @staticmethod
    def parse(fid: str) -> "FileId":
        # a "_delta" suffix addresses the delta-th key after the base fid —
        # the chunked-upload convention for count-assigned ids
        # (ref: weed/storage/needle/needle.go:123-135)
        delta = 0
        underscore = fid.rfind("_")
        if underscore > 0:
            fid, suffix = fid[:underscore], fid[underscore + 1 :]
            delta = int(suffix)
        comma = fid.find(",")
        if comma <= 0:
            raise ValueError(f"wrong fid format: {fid!r}")
        vid = parse_volume_id(fid[:comma])
        key, cookie = parse_needle_id_cookie(fid[comma + 1 :])
        # Go's NeedleId is uint64: key+delta wraps modulo 2^64 there, and
        # an unmasked Python int would overflow the 8-byte serializers
        return FileId(
            volume_id=vid, key=(key + delta) & 0xFFFFFFFFFFFFFFFF,
            cookie=cookie,
        )

    def __str__(self) -> str:
        return f"{self.volume_id},{format_needle_id_cookie(self.key, self.cookie)}"
