"""TTL codec — 2 bytes on disk: count byte + unit byte.

Ref: weed/storage/needle/volume_ttl.go (unit constants :8-17, ReadTTL :26-48,
to/from bytes :50-80).
"""

from __future__ import annotations

from dataclasses import dataclass

EMPTY = 0
MINUTE = 1
HOUR = 2
DAY = 3
WEEK = 4
MONTH = 5
YEAR = 6

_UNIT_TO_CHAR = {MINUTE: "m", HOUR: "h", DAY: "d", WEEK: "w", MONTH: "M", YEAR: "y"}
_CHAR_TO_UNIT = {v: k for k, v in _UNIT_TO_CHAR.items()}

_UNIT_MINUTES = {
    EMPTY: 0,
    MINUTE: 1,
    HOUR: 60,
    DAY: 24 * 60,
    WEEK: 7 * 24 * 60,
    MONTH: 31 * 24 * 60,
    YEAR: 365 * 24 * 60,
}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = EMPTY

    @staticmethod
    def read(ttl_string: str) -> "TTL":
        """Parse '3m'/'4h'/'5d'/'6w'/'7M'/'8y' (bare number = minutes)."""
        if not ttl_string:
            return EMPTY_TTL
        unit_ch = ttl_string[-1]
        if unit_ch.isdigit():
            count_str, unit_ch = ttl_string, "m"
        else:
            count_str = ttl_string[:-1]
        if unit_ch not in _CHAR_TO_UNIT:
            raise ValueError(f"unrecognized ttl unit: {unit_ch}")
        return TTL(count=int(count_str), unit=_CHAR_TO_UNIT[unit_ch])

    @staticmethod
    def from_bytes(b: bytes) -> "TTL":
        if b[0] == 0 and b[1] == 0:
            return EMPTY_TTL
        return TTL(count=b[0], unit=b[1])

    @staticmethod
    def from_u32(v: int) -> "TTL":
        return TTL.from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_u32(self) -> int:
        if self.count == 0:
            return 0
        return ((self.count & 0xFF) << 8) | (self.unit & 0xFF)

    @property
    def minutes(self) -> int:
        return self.count * _UNIT_MINUTES.get(self.unit, 0)

    def __str__(self) -> str:
        if self.count == 0 or self.unit == EMPTY:
            return ""
        return f"{self.count}{_UNIT_TO_CHAR[self.unit]}"


EMPTY_TTL = TTL()
