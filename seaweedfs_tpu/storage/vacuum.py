"""Vacuum (compaction): copy live needles to shadow files, then commit.

Mirrors the reference's two-phase protocol (ref: weed/storage/volume_vacuum.go):
- compact() / compact2() write .cpd/.cpx shadow files while the volume keeps
  serving writes; the super block's compaction revision is bumped in the copy;
- commit_compact() closes the volume, replays writes that raced compaction
  from the old .idx tail into the shadow files (makeup_diff,
  volume_vacuum.go:181-308), renames .cpd/.cpx over .dat/.idx and reloads.
"""

from __future__ import annotations

import os
import time

from ..types import (
    NEEDLE_MAP_ENTRY_SIZE,
    NEEDLE_PADDING_SIZE,
    TOMBSTONE_FILE_SIZE,
    to_actual_offset,
    to_offset_units,
)
from .backend import DiskFile
from .idx import entry_to_bytes, parse_entry
from .needle import Needle, read_needle_blob, read_needle_data
from .needle_map import MemDb
from .super_block import SuperBlock, read_super_block
from .volume import Volume


def compact2(v: Volume) -> None:
    """Copy live data based on the .idx (ref Compact2, volume_vacuum.go:66-89)."""
    v.is_compacting = True
    base = v.file_name()
    v.last_compact_index_offset = v.index_file_size()
    v.last_compact_revision = v.super_block.compaction_revision
    v.sync()
    _copy_data_based_on_index_file(
        base + ".dat", base + ".idx", base + ".cpd", base + ".cpx",
        v.super_block, v.version,
    )
    v.is_compacting = False


def compact(v: Volume) -> None:
    """Copy live data by scanning the .dat (ref Compact, volume_vacuum.go:37-63)."""
    v.is_compacting = True
    base = v.file_name()
    v.last_compact_index_offset = v.index_file_size()
    v.last_compact_revision = v.super_block.compaction_revision
    v.sync()

    dst = DiskFile(base + ".cpd", create=True)
    dst.truncate(0)
    sb = SuperBlock(
        version=v.super_block.version,
        replica_placement=v.super_block.replica_placement,
        ttl=v.super_block.ttl,
        compaction_revision=v.super_block.compaction_revision + 1,
        extra=v.super_block.extra,
    )
    dst.write_at(sb.to_bytes(), 0)
    new_offset = sb.block_size()
    nm = MemDb()
    now = time.time()

    def visit(n: Needle, offset: int, body: bytes) -> None:
        nonlocal new_offset
        if n.has_ttl() and n.ttl is not None and now >= n.last_modified + v.ttl.minutes * 60:
            return
        nv = v.nm.get(n.id)
        if (
            nv is not None
            and to_actual_offset(nv.offset_units) == offset
            and nv.size > 0
            and nv.size != TOMBSTONE_FILE_SIZE
        ):
            nm.set(n.id, to_offset_units(new_offset), n.size)
            blob, _, actual = n.to_bytes(v.version)
            dst.write_at(blob, new_offset)
            new_offset += actual

    v.scan(visit, read_body=True)
    dst.close()
    nm.save_to_idx(base + ".cpx")
    v.is_compacting = False


def commit_compact(v: Volume) -> Volume:
    """Swap shadow files in, absorbing racing writes; returns the reloaded
    volume (ref CommitCompact, volume_vacuum.go:91-156)."""
    base = v.file_name()
    v.is_compacting = True
    with v._lock:
        v.close()
        try:
            _makeup_diff(
                v, base + ".cpd", base + ".cpx", base + ".dat", base + ".idx"
            )
        except Exception:
            os.remove(base + ".cpd")
            os.remove(base + ".cpx")
            raise
        os.rename(base + ".cpd", base + ".dat")
        os.rename(base + ".cpx", base + ".idx")
    return Volume(
        v.dir,
        v.collection,
        v.id,
        create=False,
        needle_map_kind=getattr(v, "needle_map_kind", "memory"),
    )


def cleanup_compact(v: Volume) -> None:
    base = v.file_name()
    for ext in (".cpd", ".cpx"):
        try:
            os.remove(base + ext)
        except FileNotFoundError:
            pass


def _copy_data_based_on_index_file(
    src_dat: str, src_idx: str, dst_dat: str, dst_idx: str,
    sb: SuperBlock, version: int,
) -> None:
    """Ref copyDataBasedOnIndexFile (volume_vacuum.go:381-447)."""
    old_nm = MemDb()
    old_nm.load_from_idx(src_idx)
    src = DiskFile(src_dat, create=False, read_only=True)
    dst = DiskFile(dst_dat, create=True)
    dst.truncate(0)

    new_sb = SuperBlock(
        version=sb.version,
        replica_placement=sb.replica_placement,
        ttl=sb.ttl,
        compaction_revision=sb.compaction_revision + 1,
        extra=sb.extra,
    )
    dst.write_at(new_sb.to_bytes(), 0)
    new_offset = new_sb.block_size()
    new_nm = MemDb()
    now = time.time()

    def visit(value) -> None:
        nonlocal new_offset
        if value.offset_units == 0 or value.size == TOMBSTONE_FILE_SIZE:
            return
        try:
            n = read_needle_data(
                src, to_actual_offset(value.offset_units), value.size, version
            )
        except Exception:
            return
        if n.has_ttl() and n.ttl is not None and now >= n.last_modified + sb.ttl.minutes * 60:
            return
        new_nm.set(n.id, to_offset_units(new_offset), n.size)
        blob, _, actual = n.to_bytes(sb.version)
        dst.write_at(blob, new_offset)
        new_offset += actual

    old_nm.ascending_visit(visit)
    src.close()
    dst.close()
    new_nm.save_to_idx(dst_idx)


def _makeup_diff(
    v: Volume, new_dat: str, new_idx: str, old_dat: str, old_idx: str
) -> None:
    """Replay idx-tail updates that raced compaction into the shadow files
    (ref makeupDiff, volume_vacuum.go:181-308)."""
    idx_size = os.path.getsize(old_idx)
    if idx_size % NEEDLE_MAP_ENTRY_SIZE != 0:
        raise ValueError(f"old idx size {idx_size} corrupt")
    if idx_size == 0 or idx_size <= v.last_compact_index_offset:
        return

    old_dat_f = DiskFile(old_dat, create=False, read_only=True)
    old_rev = read_super_block(old_dat_f).compaction_revision
    if old_rev != v.last_compact_revision:
        old_dat_f.close()
        raise ValueError(
            f"old dat compact revision {old_rev} != expected {v.last_compact_revision}"
        )

    # newest entry wins per key, walking the tail backwards
    updated: dict[int, tuple[int, int]] = {}
    with open(old_idx, "rb") as f:
        off = idx_size - NEEDLE_MAP_ENTRY_SIZE
        while off >= v.last_compact_index_offset:
            f.seek(off)
            key, offset_units, size = parse_entry(f.read(NEEDLE_MAP_ENTRY_SIZE))
            if key not in updated:
                updated[key] = (offset_units, size)
            off -= NEEDLE_MAP_ENTRY_SIZE
    if not updated:
        old_dat_f.close()
        return

    dst = DiskFile(new_dat, create=False)
    new_rev = read_super_block(dst).compaction_revision
    if old_rev + 1 != new_rev:
        old_dat_f.close()
        dst.close()
        raise ValueError(f"new dat compact revision {new_rev} != old {old_rev}+1")

    idx_f = DiskFile(new_idx, create=False)
    for key, (offset_units, size) in updated.items():
        offset = dst.size()
        if offset % NEEDLE_PADDING_SIZE != 0:
            offset += NEEDLE_PADDING_SIZE - offset % NEEDLE_PADDING_SIZE
        if offset_units != 0 and size != 0 and size != TOMBSTONE_FILE_SIZE:
            blob = read_needle_blob(
                old_dat_f, to_actual_offset(offset_units), size, v.version
            )
            dst.write_at(blob, offset)
            idx_f.append(entry_to_bytes(key, to_offset_units(offset), size))
        else:
            fake = Needle(id=key, cookie=0x12345678)
            fake.append_at_ns = time.time_ns()
            blob, _, _ = fake.to_bytes(v.version)
            dst.write_at(blob, offset)
            idx_f.append(entry_to_bytes(key, 0, size))
    old_dat_f.close()
    dst.close()
    idx_f.close()
