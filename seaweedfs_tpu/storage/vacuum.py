"""Vacuum (compaction): copy live needles to shadow files, then commit.

Mirrors the reference's two-phase protocol (ref: weed/storage/volume_vacuum.go):
- compact() / compact2() write .cpd/.cpx shadow files while the volume keeps
  serving writes; the super block's compaction revision is bumped in the copy;
- commit_compact() closes the volume, replays writes that raced compaction
  from the old .idx tail into the shadow files (makeup_diff,
  volume_vacuum.go:181-308), renames .cpd/.cpx over .dat/.idx and reloads.

The vacuum-plane fast path (the compaction analogue of the PR 3 rebuild
pipeline): `compact2` no longer re-reads, re-parses and re-serializes one
needle at a time. It walks the live index in OFFSET order, coalesces
adjacent live records into multi-megabyte extents, and moves them as raw
bytes with a double-buffered readahead ring (or zero-copy mmap source
views — a one-time measured race picks the host structure), emitting the
key-sorted .cpx in one vectorized pass. Per-stage walls land in
`LAST_VACUUM_STAGES` / the `vacuum_stage_seconds` metric and the executed
structure in `LAST_VACUUM_ROUTE`. Optional CRC verification
(`SEAWEEDFS_TPU_VACUUM_VERIFY` / verify=True) re-parses every copied
record through the same CRC-verifying needle parser the scrubber uses, so
a verified vacuum doubles as a scrub pass over the live set. The
per-needle loop survives as `_copy_naive` — the benchmark baseline and
the fallback for TTL volumes (expiry needs the per-needle timestamps).

Crash safety: `commit_compact` renames .cpd over .dat and then .cpx over
.idx. Volume load (`sweep_compaction_shadows`) repairs every interruption
point: shadows from a compaction that never committed are swept; a crash
between the two renames (new .dat, old .idx, orphan .cpx) is completed by
renaming the .cpx into place.
"""

from __future__ import annotations

import os
import threading
import time

from ..types import (
    NEEDLE_CHECKSUM_SIZE,
    NEEDLE_HEADER_SIZE,
    NEEDLE_MAP_ENTRY_SIZE,
    NEEDLE_PADDING_SIZE,
    TIMESTAMP_SIZE,
    TOMBSTONE_FILE_SIZE,
    VERSION3,
    to_actual_offset,
    to_offset_units,
)
from ..util import faults
from .backend import DiskFile
from .idx import entries_to_bytes, entry_to_bytes, parse_entry, parse_index_bytes
from .needle import Needle, read_needle_blob, read_needle_data
from .needle_map import MemDb
from .super_block import SuperBlock, read_super_block
from .volume import Volume

# coalesced extents are capped so the readahead ring stays a few buffers
# of bounded size (a single over-sized record still moves in one piece)
EXTENT_TARGET = 4 << 20
# readahead depth of the pread ring: reader stays this many extents ahead
RING_DEPTH = 4

# per-stage walls of the LAST COMPLETED compaction copy in this process
# (plan/read/write/verify/idx/total; the pipelined read overlaps write, so
# stage sums can exceed total). Each copy accumulates into a LOCAL dict
# and swaps it in here atomically on completion — concurrent compactions
# (the master dispatches up to vacuum_concurrency per round) cannot
# interleave half-built breakdowns; per-run numbers travel in the report
# dict `compact2`/`_copy_data_based_on_index_file` return.
LAST_VACUUM_STAGES: dict = {}
# executed structure of the last completed copy: {"route":
# "pread"|"mmap"|"naive", "extents": N, "records": N}
LAST_VACUUM_ROUTE: dict = {}
_STAGES_LOCK = threading.Lock()

_VACUUM_HOST_ROUTE: str | None = None
_VACUUM_ROUTE_LOCK = threading.Lock()


def _stage_add(stages: dict, key: str, dt: float) -> None:
    stages[key] = stages.get(key, 0.0) + dt


def _publish_stages(stages: dict, route_info: dict) -> None:
    """Metrics + module-global snapshot, atomically per completed copy."""
    try:
        from ..util.metrics import VACUUM_STAGE_SECONDS

        for stage, v in stages.items():
            if stage.endswith("_s"):
                VACUUM_STAGE_SECONDS.observe(v, stage=stage[:-2])
    except ImportError:
        pass
    with _STAGES_LOCK:
        LAST_VACUUM_STAGES.clear()
        LAST_VACUUM_STAGES.update(stages)
        LAST_VACUUM_ROUTE.clear()
        LAST_VACUUM_ROUTE.update(route_info)


def compact2(
    v: Volume, route: str | None = None, verify: bool | None = None
) -> dict:
    """Copy live data based on the .idx (ref Compact2, volume_vacuum.go:66-89)
    through the extent-coalesced fast path; falls back to the per-needle
    loop for TTL volumes (expiry is a per-record decision). Returns the
    copy report ({route, records, extents, live_bytes, stages}), also
    kept on `v.last_vacuum_report`."""
    _begin_compaction(v)
    try:
        base = v.file_name()
        v.last_compact_index_offset = v.index_file_size()
        v.last_compact_revision = v.super_block.compaction_revision
        v.sync()
        try:
            report = _copy_data_based_on_index_file(
                base + ".dat", base + ".idx", base + ".cpd", base + ".cpx",
                v.super_block, v.version, route=route, verify=verify,
            )
        except CorruptLiveRecord as e:
            # a verified vacuum found bit rot in the LIVE set: abandon the
            # compaction (shadows removed) and quarantine like a scrub hit
            cleanup_compact(v)
            v.quarantine(f"vacuum verify: {e}")
            raise
        v.last_vacuum_report = report
        return report
    finally:
        v.is_compacting = False


def _begin_compaction(v: Volume) -> None:
    """Atomic check-and-set of the compaction flag: the master has
    several independent dispatch paths (auto loop, /vol/vacuum, -run),
    and two compact2 threads interleaving writes into one volume's
    shadow pair would corrupt the copy a later commit renames live."""
    with v._lock:
        if v.is_compacting:
            raise RuntimeError(f"volume {v.id} is already compacting")
        if v.scrub_corrupt:
            # quarantined evidence must never be rewritten by vacuum —
            # the repair plane owns this volume
            raise PermissionError(f"volume {v.id} is quarantined")
        v.is_compacting = True


def compact(v: Volume) -> None:
    """Copy live data by scanning the .dat (ref Compact, volume_vacuum.go:37-63)."""
    _begin_compaction(v)
    try:
        _compact_scan(v)
    finally:
        v.is_compacting = False


def _compact_scan(v: Volume) -> None:
    base = v.file_name()
    v.last_compact_index_offset = v.index_file_size()
    v.last_compact_revision = v.super_block.compaction_revision
    v.sync()

    dst = DiskFile(base + ".cpd", create=True)
    dst.truncate(0)
    sb = SuperBlock(
        version=v.super_block.version,
        replica_placement=v.super_block.replica_placement,
        ttl=v.super_block.ttl,
        compaction_revision=v.super_block.compaction_revision + 1,
        extra=v.super_block.extra,
    )
    dst.write_at(sb.to_bytes(), 0)
    new_offset = sb.block_size()
    nm = MemDb()
    now = time.time()

    def visit(n: Needle, offset: int, body: bytes) -> None:
        nonlocal new_offset
        if n.has_ttl() and n.ttl is not None and now >= n.last_modified + v.ttl.minutes * 60:
            return
        nv = v.nm.get(n.id)
        if (
            nv is not None
            and to_actual_offset(nv.offset_units) == offset
            and nv.size > 0
            and nv.size != TOMBSTONE_FILE_SIZE
        ):
            nm.set(n.id, to_offset_units(new_offset), n.size)
            blob, _, actual = n.to_bytes(v.version)
            dst.write_at(blob, new_offset)
            new_offset += actual

    v.scan(visit, read_body=True)
    dst.close()
    nm.save_to_idx(base + ".cpx")


def commit_compact(v: Volume) -> Volume:
    """Swap shadow files in, absorbing racing writes; returns the reloaded
    volume (ref CommitCompact, volume_vacuum.go:91-156). On failure the
    old volume object keeps `is_compacting` CLEARED — a transient commit
    error must not wedge every future `_begin_compaction` retry."""
    base = v.file_name()
    v.is_compacting = True
    try:
        with v._lock:
            v.close()
            try:
                _makeup_diff(
                    v, base + ".cpd", base + ".cpx", base + ".dat",
                    base + ".idx",
                )
            except Exception:
                # .cpx FIRST: a crash between the two removes must never
                # leave ".cpx alone", which the load-time sweep reads as
                # the half-committed state and renames over the real .idx
                for ext in (".cpx", ".cpd"):
                    try:
                        os.remove(base + ext)
                    except FileNotFoundError:
                        pass  # a concurrent cleanup already swept it
                raise
            os.rename(base + ".cpd", base + ".dat")
            os.rename(base + ".cpx", base + ".idx")
            # the .idx was just replaced wholesale: any persisted lsm
            # needle-map snapshot folds a prefix of the OLD log and must
            # not survive the swap (the reload below would otherwise
            # lean on the last-entry binding alone to reject it)
            from .needle_map.lsm_map import invalidate_snapshot

            invalidate_snapshot(base)
    finally:
        v.is_compacting = False
    return Volume(
        v.dir,
        v.collection,
        v.id,
        create=False,
        needle_map_kind=getattr(v, "needle_map_kind", "memory"),
    )


def cleanup_compact(v: Volume) -> None:
    base = v.file_name()
    # .cpx before .cpd: ".cpx alone" must stay unambiguous (see
    # sweep_compaction_shadows — it means the commit's first rename ran)
    for ext in (".cpx", ".cpd"):
        try:
            os.remove(base + ext)
        except FileNotFoundError:
            pass


def sweep_compaction_shadows(base: str) -> str | None:
    """Repair the on-disk state a compaction interrupted at ANY point left
    behind (called on volume load, like PR 3's stale `.ecNN.tmp` sweep):

    - `.cpd` present (with or without `.cpx`): the compaction never reached
      the first commit rename — the live `.dat`/`.idx` are authoritative
      and the shadows are swept;
    - `.cpx` alone: the process died between `rename(.cpd -> .dat)` and
      `rename(.cpx -> .idx)` — the `.dat` IS the committed copy and the
      old `.idx` describes a file that no longer exists, so the commit is
      completed by renaming the `.cpx` into place.

    Returns "swept", "completed" or None (nothing to do)."""
    cpd, cpx = base + ".cpd", base + ".cpx"
    has_cpd, has_cpx = os.path.exists(cpd), os.path.exists(cpx)
    if not has_cpd and not has_cpx:
        return None
    if has_cpd:
        # .cpx first, so a crash mid-sweep cannot manufacture the
        # ".cpx alone" (= half-committed) state out of dead shadows
        for path in (cpx, cpd):
            try:
                os.remove(path)
            except FileNotFoundError:
                pass
        return "swept"
    # .cpx only: finish the interrupted commit
    os.replace(cpx, base + ".idx")
    return "completed"


class CorruptLiveRecord(Exception):
    """A verified vacuum re-parsed a live record and its CRC failed: the
    LIVE set carries bit rot. Compaction must not silently drop (or
    silently propagate) the record — surface it like a scrub finding."""


class _WriteBatcher:
    """Sequential-write aggregator: the destination of a compaction is
    written strictly in order, so many small extents (a fragmented volume
    where nothing coalesces — alternating live/dead records) can share one
    large write. Small extents are staged into a reused buffer flushed at
    EXTENT_TARGET; an extent already at/over the staging size bypasses the
    copy and writes directly. This is what keeps the fast path fast in the
    WORST coalescing case: syscalls per live byte drop by ~1000x."""

    __slots__ = ("_dst", "_buf", "_fill", "_off")

    def __init__(self, dst, start_off: int):
        self._dst = dst
        self._buf = bytearray(EXTENT_TARGET)
        self._fill = 0
        self._off = start_off

    def add(self, data) -> None:
        width = len(data)
        if self._fill and self._fill + width > EXTENT_TARGET:
            self.flush()
        if width >= EXTENT_TARGET:
            self._dst.write_at(data, self._off)
            self._off += width
            return
        self._buf[self._fill : self._fill + width] = data
        self._fill += width

    def flush(self) -> None:
        if self._fill:
            self._dst.write_at(
                memoryview(self._buf)[: self._fill], self._off
            )
            self._off += self._fill
            self._fill = 0


# ------------------------------------------------ extent-coalesced copy --


def _calibrate_vacuum_route() -> str:
    """Race the two copy structures once per process on a synthetic extent
    set and remember the winner: 'pread' (double-buffered readahead ring
    into reused buffers) or 'mmap' (zero-copy source views). Same
    rationale as the rebuild plane's route race: the ranking is
    hardware-dependent (guest-fault-path cost vs buffer-copy cost) and a
    measured race picks reliably where a point probe flip-flops.
    `SEAWEEDFS_TPU_VACUUM_ROUTE` forces a route without racing."""
    global _VACUUM_HOST_ROUTE
    forced = os.environ.get("SEAWEEDFS_TPU_VACUUM_ROUTE", "")
    if forced in ("pread", "mmap"):
        return forced
    if _VACUUM_HOST_ROUTE is not None:
        return _VACUUM_HOST_ROUTE
    with _VACUUM_ROUTE_LOCK:
        if _VACUUM_HOST_ROUTE is not None:
            return _VACUUM_HOST_ROUTE
        import shutil
        import tempfile

        size = 32 << 20
        use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
        if use_dir is not None:
            try:
                if shutil.disk_usage(use_dir).free < size * 3:
                    use_dir = None
            except OSError:
                use_dir = None
        d = None
        try:
            d = tempfile.mkdtemp(prefix="vacuum_cal_", dir=use_dir)
            src_path = os.path.join(d, "src.dat")
            block = b"\xa5\x5a\xc3" * (1 << 20)
            with open(src_path, "wb") as f:
                left = size
                while left > 0:
                    f.write(block[: min(left, len(block))])
                    left -= len(block)
            # synthetic live set mixing both fragmentation regimes: large
            # coalesced runs (1MB extents, 64KB gaps) over the first half
            # and heavy fragmentation (8KB extents, 8KB gaps — nothing
            # coalesces) over the second, so the race rewards the route
            # that handles BOTH shapes
            extents = []
            off = 0
            while off + (1 << 20) <= size // 2:
                extents.append((off, 1 << 20))
                off += (1 << 20) + (64 << 10)
            off = size // 2
            while off + (8 << 10) <= size:
                extents.append((off, 8 << 10))
                off += 16 << 10
            best = ("pread", 0.0)
            for rep in range(2):
                order = ("pread", "mmap") if rep % 2 == 0 else ("mmap", "pread")
                for name in order:
                    dst_path = os.path.join(d, f"dst_{name}.dat")
                    t0 = time.perf_counter()
                    try:
                        dst = DiskFile(dst_path, create=True)
                        try:
                            if name == "pread":
                                _copy_extents_pread(
                                    src_path, dst, extents, 0, None, False,
                                    None, 0,
                                )
                            else:
                                _copy_extents_mmap(
                                    src_path, dst, extents, 0, None, False,
                                    None, 0,
                                )
                        finally:
                            dst.close()
                    except Exception:
                        continue
                    g = sum(w for _o, w in extents) / max(
                        time.perf_counter() - t0, 1e-9
                    )
                    if g > best[1]:
                        best = (name, g)
            _VACUUM_HOST_ROUTE = best[0]
        except Exception:
            _VACUUM_HOST_ROUTE = "pread"
        finally:
            if d is not None:
                shutil.rmtree(d, ignore_errors=True)
        return _VACUUM_HOST_ROUTE


def _live_entries(src_idx: str, version: int):
    """Replay the .idx log (newest entry wins) and return the live set as
    numpy columns plus per-record on-disk lengths: (keys u64[n],
    offsets i64[n] actual bytes, sizes u32[n], rec_bytes i64[n]).
    Fully vectorized: "newest wins" is each key's LAST occurrence, which
    np.unique over the reversed key column hands back directly."""
    import numpy as np

    with open(src_idx, "rb") as f:
        raw = f.read()
    keys, offsets, sizes = parse_index_bytes(raw)
    n = len(keys)
    if n == 0:
        z = np.zeros(0, dtype=np.int64)
        return keys, z, sizes, z
    uniq_keys, rev_first = np.unique(keys[::-1], return_index=True)
    last = n - 1 - rev_first  # index of each key's newest entry
    off_units = offsets[last].astype(np.int64)
    sz = sizes[last]
    alive = (off_units != 0) & (sz != np.uint32(TOMBSTONE_FILE_SIZE))
    k = uniq_keys[alive]
    off_actual = off_units[alive] * NEEDLE_PADDING_SIZE
    sz = sz[alive]
    base = (
        NEEDLE_HEADER_SIZE
        + sz.astype(np.int64)
        + NEEDLE_CHECKSUM_SIZE
        + (TIMESTAMP_SIZE if version == VERSION3 else 0)
    )
    rec = base + (NEEDLE_PADDING_SIZE - base % NEEDLE_PADDING_SIZE)
    return k, off_actual, sz, rec


def _coalesce(src_offs, rec_bytes) -> list[tuple[int, int]]:
    """Merge OFFSET-SORTED adjacent records into extents of up to
    EXTENT_TARGET bytes -> [(src_offset, width)]."""
    extents: list[tuple[int, int]] = []
    start = None
    width = 0
    for off, rec in zip(src_offs.tolist(), rec_bytes.tolist()):
        if start is not None and off == start + width and width < EXTENT_TARGET:
            width += rec
            continue
        if start is not None:
            extents.append((start, width))
        start, width = off, rec
    if start is not None:
        extents.append((start, width))
    return extents


def _verify_extent(
    buf, src_off: int, entries, version: int
) -> None:
    """Re-parse every record inside one copied extent through the
    CRC-verifying needle parser (the scrubber's check, applied to the
    bytes vacuum is about to re-home). `entries` is the (key, src_offset,
    size, rec_bytes) rows that fall inside this extent."""
    mv = memoryview(buf)
    for key, off, size, rec in entries:
        rel = off - src_off
        blob = mv[rel : rel + rec]
        try:
            n = Needle()
            n.read_bytes(blob, off, size, version)
        except Exception as e:
            raise CorruptLiveRecord(
                f"record key {key:#x} at {off} failed verification: {e}"
            ) from None
        if n.id != key:
            raise CorruptLiveRecord(
                f"record at {off} carries id {n.id:#x}, index says {key:#x}"
            )
    try:
        from ..util.metrics import SCRUB_BYTES

        SCRUB_BYTES.inc(len(buf), kind="vacuum")
    except ImportError:
        pass


# span planning: dead bytes are worth reading through when that fuses
# syscalls — but never more dead than live (amplification <= 2x) and never
# a single gap beyond this (a truly dead region is just skipped)
SPAN_GAP_TOLERANCE = 1 << 20
SPAN_TARGET = 2 * EXTENT_TARGET


def _span_batches(
    extents: list[tuple[int, int]]
) -> list[tuple[int, int, int, int]]:
    """Group consecutive extents into contiguous READ SPANS ->
    [(span_start, span_width, i_lo, i_hi)] (extent index range, i_hi
    exclusive). A span is pread/faulted in one piece — small dead gaps
    are read through and dropped by the gather — so the syscall count
    scales with spans, not records."""
    spans: list[tuple[int, int, int, int]] = []
    if not extents:
        return spans
    i_lo = 0
    span_start, width = extents[0]
    live = width
    for i in range(1, len(extents)):
        off, w = extents[i]
        gap = off - (span_start + width)
        new_width = off + w - span_start
        dead = new_width - (live + w)
        if (
            gap > SPAN_GAP_TOLERANCE
            or new_width > SPAN_TARGET
            or dead > live
        ):
            spans.append((span_start, width, i_lo, i))
            i_lo, span_start, live = i, off, w
            width = w
            continue
        width = new_width
        live += w
    spans.append((span_start, width, i_lo, len(extents)))
    return spans


def _emit_span(
    span_buf,
    span_start: int,
    extents: list[tuple[int, int]],
    i_lo: int,
    i_hi: int,
    batcher: "_WriteBatcher",
    verify: bool,
    verify_rows,
    version: int,
    stages: dict,
) -> None:
    """Writer-side half of one span: optionally CRC-verify each record in
    place, then squeeze the live bytes out (each extent is one C-level
    slice copy into the batcher's staging buffer — the dead gaps simply
    are not copied) and hand them to the sequential write batcher. A
    gap-free span skips the per-extent loop entirely."""
    mv = memoryview(span_buf)
    try:
        if verify:
            t0 = time.perf_counter()
            for i in range(i_lo, i_hi):
                off, width = extents[i]
                rel = off - span_start
                _verify_extent(
                    mv[rel : rel + width], off, verify_rows[i], version
                )
            _stage_add(stages, "verify_s", time.perf_counter() - t0)
        t0 = time.perf_counter()
        if i_hi - i_lo == 1 and extents[i_lo][1] == len(span_buf):
            batcher.add(mv)  # gap-free span: already the dst byte image
        else:
            for i in range(i_lo, i_hi):
                off, width = extents[i]
                rel = off - span_start
                batcher.add(mv[rel : rel + width])
        _stage_add(stages, "write_s", time.perf_counter() - t0)
    finally:
        mv.release()


def _copy_extents_pread(
    src_path: str,
    dst,
    extents: list[tuple[int, int]],
    dst_start: int,
    verify_rows,
    verify: bool,
    bucket,
    version: int,
    stages: dict | None = None,
) -> None:
    """Double-buffered readahead ring: a reader thread preads whole SPANS
    (consecutive extents plus bounded dead gaps — one syscall per
    multi-MB span instead of one per record) while the main thread
    verifies (optionally), gathers the live bytes in one vectorized pass
    and writes them IN ORDER. With an active fault plan the reader goes
    through the DiskFile read seam extent by extent instead, so injected
    bitflips/EIO/crashes fire exactly as on any other read."""
    import queue as _queue

    if stages is None:
        stages = {}  # calibration runs without a stage sink
    done = object()
    ring: _queue.Queue = _queue.Queue(maxsize=RING_DEPTH)
    stop = threading.Event()
    seam = faults._PLAN is not None
    if seam:
        spans = [
            (extents[i][0], extents[i][1], i, i + 1)
            for i in range(len(extents))
        ]
    else:
        spans = _span_batches(extents)

    def put(item) -> None:
        while not stop.is_set():
            try:
                ring.put(item, timeout=0.05)
                return
            except _queue.Full:
                continue

    def reader() -> None:
        fd = None
        src = None
        try:
            if seam:
                src = DiskFile(src_path, create=False, read_only=True)
            else:
                fd = os.open(src_path, os.O_RDONLY)
            for si, (span_start, width, i_lo, i_hi) in enumerate(spans):
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                if seam:
                    buf = src.read_at(width, span_start)
                    if len(buf) < width:
                        raise IOError(
                            f"short read at {span_start}: "
                            f"{len(buf)} < {width}"
                        )
                else:
                    buf = bytearray(width)
                    mv = memoryview(buf)
                    pos = 0
                    while pos < width:
                        n = os.preadv(
                            fd, [mv[pos:width]], span_start + pos
                        )
                        if n == 0:
                            raise IOError(f"short read at {span_start}")
                        pos += n
                    mv.release()
                _stage_add(stages, "read_s", time.perf_counter() - t0)
                put((si, buf))
            put(done)
        except BaseException as e:  # incl. SimulatedCrash (BaseException)
            put(e)
        finally:
            if src is not None:
                src.close()
            if fd is not None:
                os.close(fd)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    batcher = _WriteBatcher(dst, dst_start)
    try:
        while True:
            item = ring.get()
            if item is done:
                break
            if isinstance(item, BaseException):
                raise item
            si, buf = item
            span_start, width, i_lo, i_hi = spans[si]
            if bucket is not None:
                bucket.consume(width)
            _emit_span(
                buf, span_start, extents, i_lo, i_hi, batcher, verify,
                verify_rows, version, stages,
            )
        t0 = time.perf_counter()
        batcher.flush()
        _stage_add(stages, "write_s", time.perf_counter() - t0)
    finally:
        stop.set()
        t.join()


def _copy_extents_mmap(
    src_path: str,
    dst,
    extents: list[tuple[int, int]],
    dst_start: int,
    verify_rows,
    verify: bool,
    bucket,
    version: int,
    stages: dict | None = None,
) -> None:
    """Zero-copy source views: the .dat is mmapped and each extent is
    written straight from a memoryview slice (page-cache -> dst with no
    intermediate buffer copy)."""
    import mmap

    if stages is None:
        stages = {}  # calibration runs without a stage sink
    with open(src_path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size == 0:
            return
        mm = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        mv = memoryview(mm)
        try:
            batcher = _WriteBatcher(dst, dst_start)
            for span_start, width, i_lo, i_hi in _span_batches(extents):
                if bucket is not None:
                    bucket.consume(width)
                view = mv[span_start : span_start + width]
                try:
                    _emit_span(
                        view, span_start, extents, i_lo, i_hi, batcher,
                        verify, verify_rows, version, stages,
                    )
                finally:
                    view.release()
            t0 = time.perf_counter()
            batcher.flush()
            _stage_add(stages, "write_s", time.perf_counter() - t0)
        finally:
            try:
                mv.release()
                mm.close()
            except BufferError:
                # an exception mid-verify can pin slices in live traceback
                # frames; the map closes when the frames are collected
                pass


def _copy_data_based_on_index_file(
    src_dat: str, src_idx: str, dst_dat: str, dst_idx: str,
    sb: SuperBlock, version: int,
    route: str | None = None,
    verify: bool | None = None,
    bucket=None,
) -> dict:
    """Extent-coalesced fast copy (ref copyDataBasedOnIndexFile,
    volume_vacuum.go:381-447, rebuilt in the mold of rebuild_ec_files):

    1. replay the .idx into the live set (vectorized parse, newest wins);
    2. sort by source offset and coalesce adjacent records into extents;
    3. move extents as raw bytes — records are position-independent, so a
       straight byte copy IS the compaction — through the measured-race
       winner (pread ring / mmap views), writes strictly in order;
    4. emit the key-sorted .cpx in one vectorized pass.

    verify=True (or SEAWEEDFS_TPU_VACUUM_VERIFY=1) re-parses every copied
    record through the CRC-verifying parser (vacuum doubles as a scrub
    pass; CorruptLiveRecord aborts the compaction). `bucket` (or the
    shared maintenance budget) rate-shapes the copy. TTL volumes take the
    per-needle `_copy_naive` path — expiry is a per-record decision.
    Returns {route, records, extents, live_bytes, stages}.
    """
    stages: dict = {}
    t_enter = time.perf_counter()
    if verify is None:
        verify = os.environ.get(
            "SEAWEEDFS_TPU_VACUUM_VERIFY", ""
        ).lower() in ("1", "true", "on", "yes")
    if bucket is None:
        from .maintenance import plane_bucket

        bucket = plane_bucket("vacuum")

    if sb.ttl is not None and getattr(sb.ttl, "minutes", 0):
        # TTL expiry needs each record's last_modified: per-needle path
        report = _copy_naive(
            src_dat, src_idx, dst_dat, dst_idx, sb, version, bucket=bucket
        )
        stages["total_s"] = time.perf_counter() - t_enter
        _publish_stages(stages, {"route": "naive", **report})
        return {"route": "naive", "stages": stages, **report}

    import numpy as np

    new_sb = SuperBlock(
        version=sb.version,
        replica_placement=sb.replica_placement,
        ttl=sb.ttl,
        compaction_revision=sb.compaction_revision + 1,
        extra=sb.extra,
    )

    t0 = time.perf_counter()
    keys, src_offs, sizes, rec_bytes = _live_entries(src_idx, version)
    dat_size = os.path.getsize(src_dat)
    # entries whose extent runs past the .dat cannot be copied (the naive
    # loop skipped them via its failed-read except) — drop, don't crash
    ok = (src_offs + rec_bytes) <= dat_size
    keys, src_offs, sizes, rec_bytes = (
        keys[ok], src_offs[ok], sizes[ok], rec_bytes[ok],
    )
    order = np.argsort(src_offs, kind="stable")
    keys, src_offs, sizes, rec_bytes = (
        keys[order], src_offs[order], sizes[order], rec_bytes[order],
    )
    data_start = new_sb.block_size()
    dst_offs = data_start + np.concatenate(
        ([0], np.cumsum(rec_bytes)[:-1])
    ) if len(keys) else np.zeros(0, dtype=np.int64)
    extents = _coalesce(src_offs, rec_bytes)
    verify_rows = None
    if verify and extents:
        rows = list(
            zip(keys.tolist(), src_offs.tolist(), sizes.tolist(),
                rec_bytes.tolist())
        )
        verify_rows = []
        i = 0
        for off, width in extents:
            group = []
            while i < len(rows) and rows[i][1] < off + width:
                group.append(rows[i])
                i += 1
            verify_rows.append(group)
    _stage_add(stages, "plan_s", time.perf_counter() - t0)

    if route is None:
        # an active fault plan must see every byte cross the read/write
        # seams — mmap views would bypass the read seam entirely
        route = (
            "pread"
            if faults._PLAN is not None
            else _calibrate_vacuum_route()
        )
    route_info = {
        "route": route, "extents": len(extents), "records": len(keys),
    }

    dst = DiskFile(dst_dat, create=True)
    try:
        dst.truncate(0)
        dst.write_at(new_sb.to_bytes(), 0)
        copier = _copy_extents_mmap if route == "mmap" else _copy_extents_pread
        copier(
            src_dat, dst, extents, data_start, verify_rows, verify, bucket,
            version, stages,
        )
    except Exception:
        # a FAILED copy tidies its shadow; a SimulatedCrash (BaseException)
        # leaves the torn .cpd behind exactly as a killed process would —
        # the load-time shadow sweep owns that state
        try:
            os.remove(dst_dat)
        except OSError:
            pass
        raise
    finally:
        dst.close()

    t0 = time.perf_counter()
    korder = np.argsort(keys, kind="stable")
    idx_bytes = entries_to_bytes(
        keys[korder],
        (dst_offs[korder] // NEEDLE_PADDING_SIZE).astype(np.uint64),
        sizes[korder],
    )
    idx_f = DiskFile(dst_idx, create=True)
    try:
        idx_f.truncate(0)
        if idx_bytes:
            idx_f.write_at(idx_bytes, 0)
    finally:
        idx_f.close()
    _stage_add(stages, "idx_s", time.perf_counter() - t0)

    live_bytes = int(rec_bytes.sum()) if len(keys) else 0
    stages["total_s"] = time.perf_counter() - t_enter
    _publish_stages(stages, route_info)
    return {
        "route": route,
        "records": int(len(keys)),
        "extents": len(extents),
        "live_bytes": live_bytes,
        "stages": stages,
    }


def _copy_naive(
    src_dat: str, src_idx: str, dst_dat: str, dst_idx: str,
    sb: SuperBlock, version: int, bucket=None,
) -> dict:
    """The pre-fast-path reference structure (one needle at a time:
    pread + CRC parse + re-serialize + write). Kept as the benchmark
    baseline and the TTL-volume path (per-record expiry)."""
    old_nm = MemDb()
    old_nm.load_from_idx(src_idx)
    src = DiskFile(src_dat, create=False, read_only=True)
    dst = DiskFile(dst_dat, create=True)
    dst.truncate(0)

    new_sb = SuperBlock(
        version=sb.version,
        replica_placement=sb.replica_placement,
        ttl=sb.ttl,
        compaction_revision=sb.compaction_revision + 1,
        extra=sb.extra,
    )
    dst.write_at(new_sb.to_bytes(), 0)
    new_offset = new_sb.block_size()
    new_nm = MemDb()
    now = time.time()
    records = 0

    def visit(value) -> None:
        nonlocal new_offset, records
        if value.offset_units == 0 or value.size == TOMBSTONE_FILE_SIZE:
            return
        try:
            n = read_needle_data(
                src, to_actual_offset(value.offset_units), value.size, version
            )
        except Exception:
            return
        if n.has_ttl() and n.ttl is not None and now >= n.last_modified + sb.ttl.minutes * 60:
            return
        new_nm.set(n.id, to_offset_units(new_offset), n.size)
        blob, _, actual = n.to_bytes(sb.version)
        if bucket is not None:
            bucket.consume(actual)
        dst.write_at(blob, new_offset)
        new_offset += actual
        records += 1

    old_nm.ascending_visit(visit)
    src.close()
    dst.close()
    new_nm.save_to_idx(dst_idx)
    return {"records": records, "live_bytes": new_offset - new_sb.block_size()}


def _makeup_diff(
    v: Volume, new_dat: str, new_idx: str, old_dat: str, old_idx: str
) -> None:
    """Replay idx-tail updates that raced compaction into the shadow files
    (ref makeupDiff, volume_vacuum.go:181-308)."""
    idx_size = os.path.getsize(old_idx)
    if idx_size % NEEDLE_MAP_ENTRY_SIZE != 0:
        raise ValueError(f"old idx size {idx_size} corrupt")
    if idx_size == 0 or idx_size <= v.last_compact_index_offset:
        return

    old_dat_f = DiskFile(old_dat, create=False, read_only=True)
    old_rev = read_super_block(old_dat_f).compaction_revision
    if old_rev != v.last_compact_revision:
        old_dat_f.close()
        raise ValueError(
            f"old dat compact revision {old_rev} != expected {v.last_compact_revision}"
        )

    # newest entry wins per key, walking the tail backwards
    updated: dict[int, tuple[int, int]] = {}
    with open(old_idx, "rb") as f:
        off = idx_size - NEEDLE_MAP_ENTRY_SIZE
        while off >= v.last_compact_index_offset:
            f.seek(off)
            key, offset_units, size = parse_entry(f.read(NEEDLE_MAP_ENTRY_SIZE))
            if key not in updated:
                updated[key] = (offset_units, size)
            off -= NEEDLE_MAP_ENTRY_SIZE
    if not updated:
        old_dat_f.close()
        return

    dst = DiskFile(new_dat, create=False)
    new_rev = read_super_block(dst).compaction_revision
    if old_rev + 1 != new_rev:
        old_dat_f.close()
        dst.close()
        raise ValueError(f"new dat compact revision {new_rev} != old {old_rev}+1")

    idx_f = DiskFile(new_idx, create=False)
    for key, (offset_units, size) in updated.items():
        offset = dst.size()
        if offset % NEEDLE_PADDING_SIZE != 0:
            offset += NEEDLE_PADDING_SIZE - offset % NEEDLE_PADDING_SIZE
        if offset_units != 0 and size != 0 and size != TOMBSTONE_FILE_SIZE:
            blob = read_needle_blob(
                old_dat_f, to_actual_offset(offset_units), size, v.version
            )
            dst.write_at(blob, offset)
            idx_f.append(entry_to_bytes(key, to_offset_units(offset), size))
        else:
            fake = Needle(id=key, cookie=0x12345678)
            fake.append_at_ns = time.time_ns()
            blob, _, _ = fake.to_bytes(v.version)
            dst.write_at(blob, offset)
            idx_f.append(entry_to_bytes(key, 0, size))
    old_dat_f.close()
    dst.close()
    idx_f.close()
