"""Shared maintenance I/O budget: one byte/s cap over every background
plane.

Online-EC studies show background maintenance traffic is the dominant
interference source for foreground reads on warm stores (arxiv
1709.05365): each plane being individually rate-shaped is not enough when
scrub, vacuum and repair pulls run concurrently — their SUM is what the
foreground p50 sees. `MaintenanceBudget` generalizes the scrubber's token
bucket into a single bucket shared by every plane, with per-plane byte
accounting so operators can see who spent the budget.

Activation: `SEAWEEDFS_TPU_MAINT_MBPS` (MB/s across all planes) arms the
process-wide budget returned by `shared_budget()`; unset/0 means no shared
cap and each plane falls back to its own shaping (e.g. the scrubber's
`SEAWEEDFS_TPU_SCRUB_MBPS`). Planes take a `plane("scrub")` handle whose
`consume(n)` blocks until the shared bucket holds n tokens — the handle
satisfies the same duck-type as a `TokenBucket`, so every existing
`bucket.consume(...)` call site works unchanged.

**Pressure coupling (ISSUE 9):** a static MB/s cap is the right ceiling
for the steady state, but the wrong one during an overload — when the
admission gates are shedding foreground requests, ANY maintenance I/O is
stolen goodput. Every `consume()` (shared budget AND per-plane explicit
buckets routed through `plane_bucket`) therefore consults
`util/overload.global_pressure()` and sleeps extra time proportional to
the pressure: at p≥~1 (a gate shed within the last second) each consume
pays up to `SEAWEEDFS_TPU_MAINT_YIELD_MAX_S` (default 0.5s) — an
effective pause that drains the moment shedding stops, never a deadlock.
Yields are counted per plane (`maintenance_pressure_yields_total`), so a
bench/chaos run can assert maintenance actually got out of the way.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional


class TokenBucket:
    """Byte/s rate shaping for maintenance I/O. `consume(n)` blocks until
    the bucket holds n tokens; capacity (burst) defaults to one second of
    rate, so sustained throughput converges on `rate` while a tiny pass
    still finishes in one gulp. Injectable clock/sleep for tests."""

    def __init__(
        self,
        rate_bytes_per_s: float,
        capacity: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if rate_bytes_per_s <= 0:
            raise ValueError("token bucket needs a positive rate")
        self.rate = float(rate_bytes_per_s)
        self.capacity = float(capacity if capacity is not None else rate_bytes_per_s)
        self._clock = clock
        self._sleep = sleep
        self._tokens = self.capacity
        self._last = clock()
        self._lock = threading.Lock()

    def consume(self, n: int) -> float:
        """Take n tokens, sleeping as needed; returns seconds slept.
        Requests larger than the burst capacity are paid in capacity-sized
        installments (they must not deadlock, just take proportionally
        longer)."""
        slept = 0.0
        need = float(n)
        while need > 0:
            with self._lock:
                now = self._clock()
                self._tokens = min(
                    self.capacity, self._tokens + (now - self._last) * self.rate
                )
                self._last = now
                chunk = min(need, self.capacity)
                if self._tokens >= chunk:
                    self._tokens -= chunk
                    need -= chunk
                    continue
                wait = max((chunk - self._tokens) / self.rate, 0.001)
            self._sleep(wait)
            slept += wait
        return slept


def _yield_max_s() -> float:
    try:
        return float(
            os.environ.get("SEAWEEDFS_TPU_MAINT_YIELD_MAX_S", "") or 0.5
        )
    except ValueError:
        return 0.5


def yield_for_pressure(
    plane: str,
    base_s: float,
    sleep: Callable[[float], None] = time.sleep,
    pressure: Optional[Callable[[], float]] = None,
) -> float:
    """Sleep extra time proportional to foreground pressure; returns the
    seconds yielded (0.0 — one float compare — in the common no-pressure
    case). `base_s` is the uncontended wall this consume would take at
    the configured rate: under pressure p the plane's effective rate
    drops to rate*(1-p), i.e. extra = base * p/(1-p), clamped to the
    per-consume cap so p→1 means "pause", never "hang forever"."""
    if pressure is None:
        pressure = _global_pressure
    p = pressure()
    if p < 0.05:
        return 0.0
    p = min(p, 0.999)
    extra = min(base_s * (p / (1.0 - p)), _yield_max_s())
    if extra <= 0.0:
        return 0.0
    try:
        from ..util.metrics import MAINTENANCE_YIELDS

        MAINTENANCE_YIELDS.inc(plane=plane)
    except ImportError:
        pass
    sleep(extra)
    return extra


def _global_pressure() -> float:
    from ..util.overload import global_pressure

    return global_pressure()


class _PlaneHandle:
    """One plane's view of the shared budget: a TokenBucket-shaped object
    whose consumption is charged to the common bucket and attributed to
    the plane in the budget's accounting (and the maintenance_bytes_total
    metric)."""

    __slots__ = ("_budget", "plane")

    def __init__(self, budget: "MaintenanceBudget", plane: str):
        self._budget = budget
        self.plane = plane

    def consume(self, n: int) -> float:
        return self._budget.consume(n, self.plane)


class MaintenanceBudget:
    """One token bucket shared by every background plane (scrub, vacuum,
    repair), so their COMBINED read+write traffic stays under a single
    MB/s cap no matter how many planes happen to run at once."""

    def __init__(
        self,
        rate_mbps: float,
        capacity_bytes: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.rate_mbps = float(rate_mbps)
        self.bucket = TokenBucket(
            rate_mbps * 1e6, capacity=capacity_bytes, clock=clock, sleep=sleep
        )
        self._sleep = sleep
        self._lock = threading.Lock()
        self._spent: dict[str, int] = {}
        self._slept: dict[str, float] = {}
        self._yielded: dict[str, float] = {}

    def plane(self, name: str) -> _PlaneHandle:
        return _PlaneHandle(self, name)

    def consume(self, n: int, plane: str = "other") -> float:
        slept = self.bucket.consume(n)
        # pressure coupling: yield to foreground traffic being shed by
        # the admission gates — the static cap is the ceiling, this makes
        # it dynamic (arxiv 1709.05365's interference result)
        yielded = yield_for_pressure(
            plane, float(n) / self.bucket.rate, sleep=self._sleep
        )
        slept += yielded
        with self._lock:
            self._spent[plane] = self._spent.get(plane, 0) + int(n)
            self._slept[plane] = self._slept.get(plane, 0.0) + slept
            if yielded:
                self._yielded[plane] = (
                    self._yielded.get(plane, 0.0) + yielded
                )
        try:
            from ..util.metrics import MAINTENANCE_BYTES

            MAINTENANCE_BYTES.inc(n, plane=plane)
        except ImportError:
            pass
        return slept

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rate_mbps": self.rate_mbps,
                "spent_bytes": dict(self._spent),
                "throttle_seconds": {
                    k: round(v, 3) for k, v in self._slept.items()
                },
                "pressure_yield_seconds": {
                    k: round(v, 3) for k, v in self._yielded.items()
                },
            }


_SHARED: Optional[MaintenanceBudget] = None
_SHARED_LOCK = threading.Lock()


def shared_budget() -> Optional[MaintenanceBudget]:
    """The process-wide budget armed by SEAWEEDFS_TPU_MAINT_MBPS, or None
    when no shared cap is configured (each plane shapes itself)."""
    global _SHARED
    if _SHARED is not None:
        return _SHARED
    rate = float(os.environ.get("SEAWEEDFS_TPU_MAINT_MBPS", "0") or 0)
    if rate <= 0:
        return None
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = MaintenanceBudget(rate)
        return _SHARED


def configure_shared(budget: Optional[MaintenanceBudget]) -> None:
    """Install (or clear) the process-wide budget — tests and embedders."""
    global _SHARED
    with _SHARED_LOCK:
        _SHARED = budget


class _PressureShapedBucket:
    """A plane's explicitly configured bucket, with the foreground
    pressure yield layered on top — the plane's own MB/s knob still sets
    its ceiling, but an overloaded gate makes it back off exactly like
    the shared budget's planes do. Same consume() duck-type."""

    __slots__ = ("_bucket", "plane")

    def __init__(self, bucket, plane: str):
        self._bucket = bucket
        self.plane = plane

    def consume(self, n: int) -> float:
        slept = self._bucket.consume(n)
        rate = getattr(self._bucket, "rate", 0.0)
        base_s = float(n) / rate if rate > 0 else 0.01
        return slept + yield_for_pressure(
            self.plane, base_s, sleep=getattr(self._bucket, "_sleep", time.sleep)
        )


def plane_bucket(plane: str, explicit=None):
    """The rate shaper a plane should use: an explicitly configured bucket
    wins (the plane's own knob, pressure-wrapped), else the shared
    budget's plane handle, else None (unshaped)."""
    if explicit is not None:
        return _PressureShapedBucket(explicit, plane)
    budget = shared_budget()
    if budget is not None:
        return budget.plane(plane)
    return None
