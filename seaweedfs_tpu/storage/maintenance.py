"""Shared maintenance I/O budget: one byte/s cap over every background
plane.

Online-EC studies show background maintenance traffic is the dominant
interference source for foreground reads on warm stores (arxiv
1709.05365): each plane being individually rate-shaped is not enough when
scrub, vacuum and repair pulls run concurrently — their SUM is what the
foreground p50 sees. `MaintenanceBudget` generalizes the scrubber's token
bucket into a single bucket shared by every plane, with per-plane byte
accounting so operators can see who spent the budget.

Activation: `SEAWEEDFS_TPU_MAINT_MBPS` (MB/s across all planes) arms the
process-wide budget returned by `shared_budget()`; unset/0 means no shared
cap and each plane falls back to its own shaping (e.g. the scrubber's
`SEAWEEDFS_TPU_SCRUB_MBPS`). Planes take a `plane("scrub")` handle whose
`consume(n)` blocks until the shared bucket holds n tokens — the handle
satisfies the same duck-type as a `TokenBucket`, so every existing
`bucket.consume(...)` call site works unchanged.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional


class TokenBucket:
    """Byte/s rate shaping for maintenance I/O. `consume(n)` blocks until
    the bucket holds n tokens; capacity (burst) defaults to one second of
    rate, so sustained throughput converges on `rate` while a tiny pass
    still finishes in one gulp. Injectable clock/sleep for tests."""

    def __init__(
        self,
        rate_bytes_per_s: float,
        capacity: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if rate_bytes_per_s <= 0:
            raise ValueError("token bucket needs a positive rate")
        self.rate = float(rate_bytes_per_s)
        self.capacity = float(capacity if capacity is not None else rate_bytes_per_s)
        self._clock = clock
        self._sleep = sleep
        self._tokens = self.capacity
        self._last = clock()
        self._lock = threading.Lock()

    def consume(self, n: int) -> float:
        """Take n tokens, sleeping as needed; returns seconds slept.
        Requests larger than the burst capacity are paid in capacity-sized
        installments (they must not deadlock, just take proportionally
        longer)."""
        slept = 0.0
        need = float(n)
        while need > 0:
            with self._lock:
                now = self._clock()
                self._tokens = min(
                    self.capacity, self._tokens + (now - self._last) * self.rate
                )
                self._last = now
                chunk = min(need, self.capacity)
                if self._tokens >= chunk:
                    self._tokens -= chunk
                    need -= chunk
                    continue
                wait = max((chunk - self._tokens) / self.rate, 0.001)
            self._sleep(wait)
            slept += wait
        return slept


class _PlaneHandle:
    """One plane's view of the shared budget: a TokenBucket-shaped object
    whose consumption is charged to the common bucket and attributed to
    the plane in the budget's accounting (and the maintenance_bytes_total
    metric)."""

    __slots__ = ("_budget", "plane")

    def __init__(self, budget: "MaintenanceBudget", plane: str):
        self._budget = budget
        self.plane = plane

    def consume(self, n: int) -> float:
        return self._budget.consume(n, self.plane)


class MaintenanceBudget:
    """One token bucket shared by every background plane (scrub, vacuum,
    repair), so their COMBINED read+write traffic stays under a single
    MB/s cap no matter how many planes happen to run at once."""

    def __init__(
        self,
        rate_mbps: float,
        capacity_bytes: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.rate_mbps = float(rate_mbps)
        self.bucket = TokenBucket(
            rate_mbps * 1e6, capacity=capacity_bytes, clock=clock, sleep=sleep
        )
        self._lock = threading.Lock()
        self._spent: dict[str, int] = {}
        self._slept: dict[str, float] = {}

    def plane(self, name: str) -> _PlaneHandle:
        return _PlaneHandle(self, name)

    def consume(self, n: int, plane: str = "other") -> float:
        slept = self.bucket.consume(n)
        with self._lock:
            self._spent[plane] = self._spent.get(plane, 0) + int(n)
            self._slept[plane] = self._slept.get(plane, 0.0) + slept
        try:
            from ..util.metrics import MAINTENANCE_BYTES

            MAINTENANCE_BYTES.inc(n, plane=plane)
        except ImportError:
            pass
        return slept

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rate_mbps": self.rate_mbps,
                "spent_bytes": dict(self._spent),
                "throttle_seconds": {
                    k: round(v, 3) for k, v in self._slept.items()
                },
            }


_SHARED: Optional[MaintenanceBudget] = None
_SHARED_LOCK = threading.Lock()


def shared_budget() -> Optional[MaintenanceBudget]:
    """The process-wide budget armed by SEAWEEDFS_TPU_MAINT_MBPS, or None
    when no shared cap is configured (each plane shapes itself)."""
    global _SHARED
    if _SHARED is not None:
        return _SHARED
    rate = float(os.environ.get("SEAWEEDFS_TPU_MAINT_MBPS", "0") or 0)
    if rate <= 0:
        return None
    with _SHARED_LOCK:
        if _SHARED is None:
            _SHARED = MaintenanceBudget(rate)
        return _SHARED


def configure_shared(budget: Optional[MaintenanceBudget]) -> None:
    """Install (or clear) the process-wide budget — tests and embedders."""
    global _SHARED
    with _SHARED_LOCK:
        _SHARED = budget


def plane_bucket(plane: str, explicit=None):
    """The rate shaper a plane should use: an explicitly configured bucket
    wins (the plane's own knob), else the shared budget's plane handle,
    else None (unshaped)."""
    if explicit is not None:
        return explicit
    budget = shared_budget()
    if budget is not None:
        return budget.plane(plane)
    return None
