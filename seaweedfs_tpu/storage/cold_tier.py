"""Cold tier: remote offload of sealed EC shard files + read-through recall.

The third verse of the paper's tiering arc (Haystack hot -> f4 warm -> cloud
cold, PAPER.md layer map / `weed/storage/backend/`): the lifecycle planner's
coldest band moves sealed `.ecNN` shard files onto a remote object backend
(`storage/tier_backend.py` registry — in tests and benches an in-tree HTTP
blob server served through `ServingCore`, so fault plans, admission gates
and tracing fire on "the cloud" too), keeping only the `.ecx`/`.vif` index
sidecars (and `.heat`) local. Reads of an offloaded shard go through a
byte-range read-through cache (`RemoteExtentCache`, the
`DegradedIntervalCache` pattern applied to remote extents), and sustained
heat recalls the shards to local disk the way re-inflation already works.

Crash discipline (the `.nmm`/`.cpx` shadow-write + sweep construction):
placement is recorded in a per-volume tier manifest `<base>.ctm` written
shadow-first (`<base>.ctm.shadow` -> fsync -> atomic rename), and the
offload/recall step order guarantees NO kill point can lose the only copy
of a shard:

offload, per shard:   (1) upload to the backend (deterministic key, so a
                          retried upload overwrites — shards are sealed)
                      (2) commit the manifest entry (shadow + rename)
                      (3) unlink the local shard file
recall, per shard:    (1) download to `<shard>.ctmp` (swept at load)
                      (2) atomic rename into place
                      (3) drop the manifest entry (shadow + rename)
                      (4) delete the remote object

A crash between (1) and (2) of offload leaves a remote orphan and the local
file — safe, the retry re-uploads over the same key. A crash between (2)
and (3) leaves BOTH copies with the manifest naming the remote one — safe
in either direction (resume-offload verifies the remote size then unlinks;
resume-recall sees the local file, drops the entry, deletes the remote).
Only after the manifest durably names the remote copy is the local file
ever unlinked. `tests/test_cold_tier.py` drives a kill-point grid over
every step to pin this.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

MANIFEST_EXT = ".ctm"
SHADOW_EXT = ".ctm.shadow"
RECALL_TMP_EXT = ".ctmp"

# read-through cache sizing: spans widened to this alignment (readahead —
# neighbouring needles on the same offloaded shard land in one remote GET)
COLD_READ_SPAN = (
    int(os.environ.get("SEAWEEDFS_TPU_COLD_READ_SPAN_KB", "128") or 128)
    * 1024
)
COLD_CACHE_BYTES = (
    int(os.environ.get("SEAWEEDFS_TPU_COLD_CACHE_MB", "32") or 32) << 20
)


# ---------------------------------------------------------------- manifest --


def manifest_path(base: str) -> str:
    return base + MANIFEST_EXT


def sweep_manifest_shadow(base: str) -> bool:
    """Drop a torn shadow left by a crash mid-commit (the `.cpd` sweep
    discipline: a shadow is never read as authority). Returns True when
    one was swept."""
    shadow = base + SHADOW_EXT
    if os.path.exists(shadow):
        try:
            os.remove(shadow)
            return True
        except OSError:
            pass
    return False


def sweep_recall_tmps(base: str) -> int:
    """Drop torn `.ecNN.ctmp` downloads left by a crash mid-recall.
    Probes the 32 candidate names directly (shard ids are bounded by the
    ShardBits width) instead of listing the directory — this runs in
    every EcVolume constructor, and an os.listdir here would make a
    10k-volume mount O(volumes x directory-entries)."""
    from .erasure_coding import to_ext

    swept = 0
    for sid in range(32):
        tmp = base + to_ext(sid) + RECALL_TMP_EXT
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
                swept += 1
            except OSError:
                pass
    return swept


def load_manifest(base: str) -> dict:
    """{shard_id: {"key": str, "size": int, "backend": str}} from
    `<base>.ctm`; {} when absent or unparseable (an unparseable manifest
    means shards may exist remotely that we cannot name — refuse to guess:
    the local files, if any, are the copies we trust)."""
    sweep_manifest_shadow(base)
    path = manifest_path(base)
    if not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return {}
    out: dict[int, dict] = {}
    for sid, ent in (d.get("shards") or {}).items():
        try:
            out[int(sid)] = {
                "key": str(ent["key"]),
                "size": int(ent.get("size", 0)),
                "backend": str(ent.get("backend", "")),
            }
        except (KeyError, TypeError, ValueError):
            continue
    return out


def save_manifest(base: str, shards: dict) -> None:
    """Commit the manifest crash-atomically: full shadow write + fsync +
    rename. An EMPTY manifest is removed outright (a volume with nothing
    offloaded carries no sidecar)."""
    path = manifest_path(base)
    if not shards:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
        return
    shadow = base + SHADOW_EXT
    payload = json.dumps(
        {
            "version": 1,
            "shards": {
                str(sid): {
                    "key": ent["key"],
                    "size": int(ent.get("size", 0)),
                    "backend": ent.get("backend", ""),
                }
                for sid, ent in shards.items()
            },
        },
        sort_keys=True,
    )
    with open(shadow, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(shadow, path)


# ------------------------------------------------------------ offload/recall --

# step-hook names, in execution order per shard — the kill-point grid in
# tests/test_cold_tier.py enumerates exactly these
OFFLOAD_STEPS = ("upload", "commit", "unlink")
RECALL_STEPS = ("download", "rename", "uncommit", "remote_delete")


def offload_shards(
    ev,
    backend,
    shard_ids: Optional[list[int]] = None,
    step_hook: Optional[Callable[[str, int], None]] = None,
    throttle: Optional[Callable[[int], object]] = None,
) -> dict:
    """Move an EcVolume's LOCAL shard files onto `backend`; returns
    {shard_id: bytes_uploaded}. Blocking (urllib/file I/O) — callers run
    it in an executor. `step_hook(step, shard_id)` fires before each step
    (the kill-point seam); `throttle(n)` is the maintenance-budget charge
    per shard (plane=lifecycle).

    Resume semantics: a shard whose manifest entry already exists skips
    the upload after verifying the remote size (a crash landed between
    commit and unlink) and proceeds straight to the unlink. The local
    file is ONLY unlinked after the manifest durably names the remote
    copy."""
    base = ev.file_name()
    manifest = load_manifest(base)
    todo = list(shard_ids) if shard_ids is not None else ev.shard_ids()
    out: dict[int, int] = {}
    for sid in todo:
        shard = ev.find_shard(sid)
        if shard is None:
            continue
        path = shard.file_name() + _to_ext(sid)
        size = os.path.getsize(path)
        if throttle is not None:
            throttle(size)
        ent = manifest.get(sid)
        if ent is None or not _remote_size_matches(backend, ent, size):
            if step_hook is not None:
                step_hook("upload", sid)
            key, uploaded = backend.copy_file(
                path,
                {
                    "volumeId": str(ev.volume_id),
                    "collection": ev.collection,
                    "ext": _to_ext(sid),
                },
            )
            if uploaded != size:
                raise IOError(
                    f"shard {ev.volume_id}.{sid}: uploaded {uploaded} of "
                    f"{size} bytes"
                )
            manifest[sid] = {
                "key": key,
                "size": size,
                "backend": backend.name,
            }
            if step_hook is not None:
                step_hook("commit", sid)
            save_manifest(base, manifest)
        if step_hook is not None:
            step_hook("unlink", sid)
        # order matters: unlink BEFORE dropping the in-memory shard so a
        # concurrent read holding the EcVolumeShard still preads the
        # unlinked-but-open file. The fd is deliberately NOT closed here:
        # a peer stream mid-VolumeEcShardRead may hold the shard object
        # across awaits, and closing under it would turn its next pread
        # into EBADF (or, after fd reuse, another file's bytes) — the
        # last reference releasing the file object closes the fd.
        try:
            os.remove(path)
        except FileNotFoundError:
            pass
        ev.note_shard_offloaded(sid, manifest[sid])
        ev.delete_shard(sid)
        out[sid] = size
        _count_tier_bytes(size, "offload")
    return out


def recall_shards(
    ev,
    get_backend: Callable[[str], object],
    step_hook: Optional[Callable[[str, int], None]] = None,
    throttle: Optional[Callable[[int], object]] = None,
    delete_remote: bool = True,
) -> dict:
    """Bring every offloaded shard of an EcVolume back to local disk;
    returns {shard_id: bytes_downloaded}. Blocking — callers run it in an
    executor. The remote object is deleted only AFTER the manifest entry
    is durably dropped; a shard whose local file already exists (crash
    between rename and uncommit) skips the download."""
    base = ev.file_name()
    manifest = load_manifest(base)
    out: dict[int, int] = {}
    for sid in sorted(manifest):
        ent = manifest[sid]
        backend = get_backend(ent.get("backend", ""))
        if backend is None:
            raise ValueError(
                f"shard {ev.volume_id}.{sid}: backend "
                f"{ent.get('backend')!r} not registered"
            )
        path = base + _to_ext(sid)
        size = int(ent.get("size", 0))
        if throttle is not None:
            throttle(size)
        if not os.path.exists(path):
            if step_hook is not None:
                step_hook("download", sid)
            tmp = path + RECALL_TMP_EXT
            got = backend.download_file(tmp, ent["key"])
            if size and got != size:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise IOError(
                    f"shard {ev.volume_id}.{sid}: recalled {got} of "
                    f"{size} bytes"
                )
            with open(tmp, "rb") as f:
                os.fsync(f.fileno())
            if step_hook is not None:
                step_hook("rename", sid)
            os.replace(tmp, path)
        if step_hook is not None:
            step_hook("uncommit", sid)
        del manifest[sid]
        save_manifest(base, manifest)
        ev.note_shard_recalled(sid)
        if delete_remote:
            if step_hook is not None:
                step_hook("remote_delete", sid)
            try:
                backend.delete_file(ent["key"])
            except Exception:
                pass  # an orphan is bytes, never lost data
        out[sid] = size or os.path.getsize(path)
        _count_tier_bytes(out[sid], "recall")
    return out


def _remote_size_matches(backend, ent: dict, size: int) -> bool:
    """Resume check: trust an existing manifest entry only when the
    remote object is really there at the recorded size."""
    try:
        f = backend.new_storage_file(ent["key"])
    except Exception:
        return False
    try:
        return int(ent.get("size", -1)) == size and f.size() == size
    except Exception:
        return False
    finally:
        try:
            f.close()
        except Exception:
            pass


def _to_ext(shard_id: int) -> str:
    from .erasure_coding import to_ext

    return to_ext(shard_id)


def _count_tier_bytes(n: int, direction: str) -> None:
    try:
        from ..util.metrics import TIER_OFFLOAD_BYTES

        TIER_OFFLOAD_BYTES.inc(n, direction=direction)
    except ImportError:
        pass


# ------------------------------------------------------- read-through cache --


class RemoteExtentCache:
    """Byte-bounded LRU of remote shard extents, keyed by
    (volume_id, shard_id, span_start) — the `DegradedIntervalCache`
    pattern applied to remote byte ranges.

    A read of an offloaded shard widens its interval to COLD_READ_SPAN
    alignment, fetches the whole span with ONE ranged remote GET, caches
    it, and serves any later interval falling inside a cached span — a
    hot offloaded shard costs one remote round trip per span instead of
    per needle. Shard files are sealed (immutable once encoded), so spans
    never go stale; recall/unmount/delete drop a volume's spans because
    the shard is no longer remote at all."""

    def __init__(
        self,
        capacity_bytes: int = COLD_CACHE_BYTES,
        span: int = COLD_READ_SPAN,
    ):
        self.capacity = capacity_bytes
        self.span = max(span, 4096)
        self._spans: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.stats = {"hits": 0, "misses": 0}

    def span_for(
        self, offset: int, size: int, shard_size: Optional[int]
    ) -> tuple[int, int]:
        """Aligned (span_start, span_size) covering [offset, offset+size);
        clamped to the shard end so the remote GET never reads short."""
        if not shard_size or offset + size > shard_size:
            return offset, size
        start = offset - (offset % self.span)
        end = offset + size
        end += (-end) % self.span
        return start, min(end, shard_size) - start

    def get(
        self, vid: int, shard_id: int, offset: int, size: int
    ) -> Optional[bytes]:
        start = offset - (offset % self.span)
        with self._lock:
            for key in ((vid, shard_id, start), (vid, shard_id, offset)):
                span = self._spans.get(key)
                if span is not None and key[2] + len(span) >= offset + size:
                    self._spans.move_to_end(key)
                    self.stats["hits"] += 1
                    _count_cache(True)
                    return span[offset - key[2] : offset - key[2] + size]
            self.stats["misses"] += 1
            _count_cache(False)
        return None

    def put(
        self, vid: int, shard_id: int, span_start: int, data: bytes
    ) -> None:
        if len(data) > self.capacity:
            return
        key = (vid, shard_id, span_start)
        with self._lock:
            old = self._spans.pop(key, None)
            if old is not None:
                self._bytes -= len(old)
            self._spans[key] = data
            self._bytes += len(data)
            while self._bytes > self.capacity and self._spans:
                _k, v = self._spans.popitem(last=False)
                self._bytes -= len(v)

    def invalidate(self, vid: int) -> int:
        with self._lock:
            doomed = [k for k in self._spans if k[0] == vid]
            for k in doomed:
                self._bytes -= len(self._spans.pop(k))
            return len(doomed)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


def _count_cache(hit: bool) -> None:
    try:
        from ..util.metrics import (
            TIER_REMOTE_CACHE_HITS,
            TIER_REMOTE_CACHE_MISSES,
        )

        (TIER_REMOTE_CACHE_HITS if hit else TIER_REMOTE_CACHE_MISSES).inc()
    except ImportError:
        pass


def read_remote_extent(
    ev,
    shard_id: int,
    offset: int,
    size: int,
    cache: Optional[RemoteExtentCache],
    get_backend: Callable[[str], object],
) -> Optional[bytes]:
    """Read [offset, offset+size) of an OFFLOADED shard through the
    read-through cache (blocking — callers run it in an executor).
    Returns None when the shard is not offloaded or the backend is
    unknown; raises on remote I/O failure (the caller decides whether to
    fall through to reconstruction)."""
    ent = ev.remote_shard(shard_id)
    if ent is None:
        return None
    if cache is not None:
        hit = cache.get(ev.volume_id, shard_id, offset, size)
        if hit is not None:
            return hit
    backend = get_backend(ent.get("backend", ""))
    if backend is None:
        return None
    shard_size = int(ent.get("size", 0)) or None
    if cache is not None:
        span_start, span_size = cache.span_for(offset, size, shard_size)
    else:
        span_start, span_size = offset, size
    f = backend.new_storage_file(ent["key"])
    try:
        data = f.read_at(span_size, span_start)
    finally:
        try:
            f.close()
        except Exception:
            pass
    if len(data) != span_size:
        raise IOError(
            f"shard {ev.volume_id}.{shard_id}: remote read returned "
            f"{len(data)} of {span_size} bytes at {span_start}"
        )
    if cache is not None:
        cache.put(ev.volume_id, shard_id, span_start, data)
    return data[offset - span_start : offset - span_start + size]
