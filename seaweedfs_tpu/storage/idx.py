"""Needle-map index (.idx/.ecx) entry codec and walker.

16-byte entries: key u64 BE | offset u32 BE (in 8-byte units) | size u32 BE
(ref: weed/storage/idx/walk.go:13-53, weed/storage/types/needle_types.go:27).

Also provides vectorized numpy parse of a whole index file — the TPU-first
path used to build index snapshots for the bulk-lookup kernel.
"""

from __future__ import annotations

from typing import BinaryIO, Callable, Iterator

import numpy as np

from ..types import (
    NEEDLE_MAP_ENTRY_SIZE,
    OFFSET_SIZE,
    bytes_to_offset,
    bytes_to_u32,
    bytes_to_u64,
    offset_to_bytes,
    u32_to_bytes,
    u64_to_bytes,
)

ROW_BATCH = 1024 * 1024  # entries per read batch when walking


def entry_to_bytes(key: int, offset_units: int, size: int) -> bytes:
    return u64_to_bytes(key) + offset_to_bytes(offset_units) + u32_to_bytes(size)


def parse_entry(b: bytes) -> tuple[int, int, int]:
    """-> (key, offset_units, size)"""
    return (
        bytes_to_u64(b[0:8]),
        bytes_to_offset(b[8 : 8 + OFFSET_SIZE]),
        bytes_to_u32(b[8 + OFFSET_SIZE : NEEDLE_MAP_ENTRY_SIZE]),
    )


def iter_index(f: BinaryIO) -> Iterator[tuple[int, int, int]]:
    """Iterate (key, offset_units, size) over an open .idx stream."""
    while True:
        chunk = f.read(NEEDLE_MAP_ENTRY_SIZE * ROW_BATCH)
        if not chunk:
            return
        usable = len(chunk) - (len(chunk) % NEEDLE_MAP_ENTRY_SIZE)
        for i in range(0, usable, NEEDLE_MAP_ENTRY_SIZE):
            yield parse_entry(chunk[i : i + NEEDLE_MAP_ENTRY_SIZE])
        if usable != len(chunk):
            return


def walk_index_file(
    f: BinaryIO, fn: Callable[[int, int, int], None]
) -> None:
    """Ref WalkIndexFile: calls fn(key, offset_units, size) per entry."""
    for key, offset_units, size in iter_index(f):
        fn(key, offset_units, size)


def parse_index_bytes(data: bytes) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized parse: -> (keys u64[n], offset_units u32|u64[n], sizes
    u32[n]); offsets widen to u64 under the 5-byte variant."""
    n = len(data) // NEEDLE_MAP_ENTRY_SIZE
    arr = np.frombuffer(data[: n * NEEDLE_MAP_ENTRY_SIZE], dtype=np.uint8).reshape(
        n, NEEDLE_MAP_ENTRY_SIZE
    )
    keys = arr[:, 0:8].copy().view(">u8").reshape(n).astype(np.uint64)
    low = arr[:, 8:12].copy().view(">u4").reshape(n)
    if OFFSET_SIZE == 5:
        offsets = low.astype(np.uint64) | (
            arr[:, 12].astype(np.uint64) << np.uint64(32)
        )
    else:
        offsets = low.astype(np.uint32)
    sizes = (
        arr[:, 8 + OFFSET_SIZE : NEEDLE_MAP_ENTRY_SIZE]
        .copy()
        .view(">u4")
        .reshape(n)
        .astype(np.uint32)
    )
    return keys, offsets, sizes


def entries_to_bytes(
    keys: np.ndarray, offset_units: np.ndarray, sizes: np.ndarray
) -> bytes:
    """Vectorized serialize of index entries (inverse of parse_index_bytes)."""
    n = len(keys)
    arr = np.empty((n, NEEDLE_MAP_ENTRY_SIZE), dtype=np.uint8)
    arr[:, 0:8] = np.ascontiguousarray(keys, dtype=">u8").view(np.uint8).reshape(n, 8)
    units = np.ascontiguousarray(offset_units, dtype=np.uint64)
    arr[:, 8:12] = (
        np.ascontiguousarray(units & np.uint64(0xFFFFFFFF), dtype=">u4")
        .view(np.uint8)
        .reshape(n, 4)
    )
    if OFFSET_SIZE == 5:
        arr[:, 12] = (units >> np.uint64(32)).astype(np.uint8)
    arr[:, 8 + OFFSET_SIZE : NEEDLE_MAP_ENTRY_SIZE] = (
        np.ascontiguousarray(sizes, dtype=">u4").view(np.uint8).reshape(n, 4)
    )
    return arr.tobytes()
