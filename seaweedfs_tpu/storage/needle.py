"""Needle (stored-file record) format, versions 1-3.

Byte-compatible with the reference (ref: weed/storage/needle/needle.go:24-44,
needle_read_write.go):

header (16B): cookie u32 | id u64 | size u32          (all big-endian)
v1 body:      data[size] | crc u32 | padding
v2 body (when data_size>0):
    data_size u32 | data | flags u8
    [name_size u8 | name]   if FLAG_HAS_NAME
    [mime_size u8 | mime]   if FLAG_HAS_MIME
    [last_modified 5B]      if FLAG_HAS_LAST_MODIFIED_DATE
    [ttl 2B]                if FLAG_HAS_TTL
    [pairs_size u16 | pairs] if FLAG_HAS_PAIRS
  then: crc u32 | padding
v3 body:      v2 body with AppendAtNs u64 between crc and padding

``size`` counts the v2 body fields only (4 + data_size + 1 + optionals,
ref needle_read_write.go:61-79); the record is padded so the total length is a
multiple of 8 — note the reference pads 1..8 bytes (never 0)
(ref needle_read_write.go:291-297).
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field

from ..types import (
    NEEDLE_CHECKSUM_SIZE,
    NEEDLE_HEADER_SIZE,
    NEEDLE_PADDING_SIZE,
    TIMESTAMP_SIZE,
    VERSION1,
    VERSION2,
    VERSION3,
    bytes_to_u16,
    bytes_to_u32,
    bytes_to_u64,
    u16_to_bytes,
    u32_to_bytes,
    u64_to_bytes,
)
from ..util.crc import masked_crc
from .ttl import TTL

FLAG_IS_COMPRESSED = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED_DATE = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES_LENGTH = 5
TTL_BYTES_LENGTH = 2

PAIR_NAME_PREFIX = "Seaweed-"

# hot-path packers (to_bytes): bound struct.Struct methods beat
# int.to_bytes-per-field by several us per needle
import struct as _struct

_pack_header = _struct.Struct(">IQI").pack_into  # cookie, id, size
_pack_u16 = _struct.Struct(">H").pack_into
_pack_u32 = _struct.Struct(">I").pack_into
_pack_u64 = _struct.Struct(">Q").pack_into


class CrcError(Exception):
    """Data on disk corrupted (CRC mismatch)."""


class NotFoundError(Exception):
    """Entry not found / size mismatch."""


def padding_length(needle_size: int, version: int) -> int:
    """Ref needle_read_write.go:291-297 — pads 1..8, never 0."""
    if version == VERSION3:
        return NEEDLE_PADDING_SIZE - (
            (NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE)
            % NEEDLE_PADDING_SIZE
        )
    return NEEDLE_PADDING_SIZE - (
        (NEEDLE_HEADER_SIZE + needle_size + NEEDLE_CHECKSUM_SIZE) % NEEDLE_PADDING_SIZE
    )


def needle_body_length(needle_size: int, version: int) -> int:
    if version == VERSION3:
        return (
            needle_size
            + NEEDLE_CHECKSUM_SIZE
            + TIMESTAMP_SIZE
            + padding_length(needle_size, version)
        )
    return needle_size + NEEDLE_CHECKSUM_SIZE + padding_length(needle_size, version)


def get_actual_size(size: int, version: int) -> int:
    """Total bytes the record occupies on disk."""
    return NEEDLE_HEADER_SIZE + needle_body_length(size, version)


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    size: int = 0

    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""
    last_modified: int = 0  # seconds; 5 bytes on disk
    ttl: TTL | None = None

    checksum: int = 0  # masked crc as stored
    append_at_ns: int = 0  # version3

    # --- flags ---
    def is_compressed(self) -> bool:
        return bool(self.flags & FLAG_IS_COMPRESSED)

    def set_is_compressed(self) -> None:
        self.flags |= FLAG_IS_COMPRESSED

    def has_name(self) -> bool:
        return bool(self.flags & FLAG_HAS_NAME)

    def set_name(self, name: bytes) -> None:
        self.name = name[:255]
        if name:
            self.flags |= FLAG_HAS_NAME

    def has_mime(self) -> bool:
        return bool(self.flags & FLAG_HAS_MIME)

    def set_mime(self, mime: bytes) -> None:
        self.mime = mime[:255]
        if mime:
            self.flags |= FLAG_HAS_MIME

    def has_last_modified_date(self) -> bool:
        return bool(self.flags & FLAG_HAS_LAST_MODIFIED_DATE)

    def set_last_modified(self, ts: int) -> None:
        self.last_modified = ts
        self.flags |= FLAG_HAS_LAST_MODIFIED_DATE

    def has_ttl(self) -> bool:
        return bool(self.flags & FLAG_HAS_TTL)

    def set_ttl(self, ttl: TTL) -> None:
        self.ttl = ttl
        if ttl.count:
            self.flags |= FLAG_HAS_TTL

    def has_pairs(self) -> bool:
        return bool(self.flags & FLAG_HAS_PAIRS)

    def set_pairs(self, pairs: bytes) -> None:
        self.pairs = pairs
        if pairs:
            self.flags |= FLAG_HAS_PAIRS

    def is_chunked_manifest(self) -> bool:
        return bool(self.flags & FLAG_IS_CHUNK_MANIFEST)

    def set_is_chunk_manifest(self) -> None:
        """Mark this needle as a chunk manifest (ref: needle.go SetIsChunkManifest,
        set from the upload's cm=true form value, needle_parse_upload.go:177)."""
        self.flags |= FLAG_IS_CHUNK_MANIFEST

    def etag(self) -> str:
        return u32_to_bytes(self.checksum).hex()

    # --- serialization ---
    def _computed_size_v2(self) -> int:
        """Ref needle_read_write.go:60-79."""
        if len(self.data) == 0:
            return 0
        size = 4 + len(self.data) + 1
        if self.has_name():
            size += 1 + min(len(self.name), 255)
        if self.has_mime():
            size += 1 + min(len(self.mime), 255)
        if self.has_last_modified_date():
            size += LAST_MODIFIED_BYTES_LENGTH
        if self.has_ttl():
            size += TTL_BYTES_LENGTH
        if self.has_pairs():
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int) -> tuple[bytes, int, int]:
        """Serialize; returns (record_bytes, size_for_index, actual_size).

        size_for_index is what goes into the needle map: len(data) for v1,
        data_size for v2/v3 — matching the reference's Append() return
        (ref needle_read_write.go:31-126).
        """
        self.checksum = masked_crc(self.data)
        if version == VERSION1:
            buf = io.BytesIO()
            self.size = len(self.data)
            buf.write(u32_to_bytes(self.cookie))
            buf.write(u64_to_bytes(self.id))
            buf.write(u32_to_bytes(self.size))
            buf.write(self.data)
            buf.write(u32_to_bytes(self.checksum))
            buf.write(b"\x00" * padding_length(self.size, version))
            return buf.getvalue(), self.size, NEEDLE_HEADER_SIZE + needle_body_length(
                self.size, version
            )
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported version {version}")

        # single preallocated buffer + pack_into: this serializer sits on
        # the per-request write path and the BytesIO/many-small-writes
        # formulation was ~40us/needle at serving QPS rates
        self.size = self._computed_size_v2()
        dlen = len(self.data)
        actual = get_actual_size(self.size, version)
        out = bytearray(actual)  # padding arrives pre-zeroed
        _pack_header(out, 0, self.cookie, self.id, self.size)
        pos = NEEDLE_HEADER_SIZE
        if dlen > 0:
            _pack_u32(out, pos, dlen)
            pos += 4
            out[pos: pos + dlen] = self.data
            pos += dlen
            out[pos] = self.flags & 0xFF
            pos += 1
            if self.has_name():
                name = self.name[:255]
                out[pos] = len(name)
                out[pos + 1: pos + 1 + len(name)] = name
                pos += 1 + len(name)
            if self.has_mime():
                mime = self.mime[:255]
                out[pos] = len(mime)
                out[pos + 1: pos + 1 + len(mime)] = mime
                pos += 1 + len(mime)
            if self.has_last_modified_date():
                out[pos: pos + LAST_MODIFIED_BYTES_LENGTH] = u64_to_bytes(
                    self.last_modified
                )[8 - LAST_MODIFIED_BYTES_LENGTH:]
                pos += LAST_MODIFIED_BYTES_LENGTH
            if self.has_ttl() and self.ttl is not None:
                out[pos: pos + TTL_BYTES_LENGTH] = self.ttl.to_bytes()
                pos += TTL_BYTES_LENGTH
            if self.has_pairs():
                _pack_u16(out, pos, len(self.pairs))
                pos += 2
                out[pos: pos + len(self.pairs)] = self.pairs
                pos += len(self.pairs)
        _pack_u32(out, pos, self.checksum)
        pos += 4
        if version == VERSION3:
            _pack_u64(out, pos, self.append_at_ns)
        return bytes(out), dlen, actual

    # --- parsing ---
    def parse_header(self, b: bytes) -> None:
        self.cookie = bytes_to_u32(b[0:4])
        self.id = bytes_to_u64(b[4:12])
        self.size = bytes_to_u32(b[12:16])

    def _read_data_v2(self, b) -> None:
        """Ref needle_read_write.go:212-271.

        `b` may be a memoryview over the pread blob: `data` is kept as a
        zero-copy slice of it (serving renders straight from the buffer —
        copying every body was measurable at read-QPS rates), while the
        small optional fields are materialized as bytes so downstream
        `.decode()`-style consumers keep working."""
        index, n = 0, len(b)
        if index < n:
            data_size = bytes_to_u32(b[index : index + 4])
            index += 4
            if data_size + index > n:
                raise ValueError("index out of range 1")
            self.data = b[index : index + data_size]
            index += data_size
            self.flags = b[index]
            index += 1
        if index < n and self.has_name():
            name_size = b[index]
            index += 1
            if name_size + index > n:
                raise ValueError("index out of range 2")
            self.name = bytes(b[index : index + name_size])
            index += name_size
        if index < n and self.has_mime():
            mime_size = b[index]
            index += 1
            if mime_size + index > n:
                raise ValueError("index out of range 3")
            self.mime = bytes(b[index : index + mime_size])
            index += mime_size
        if index < n and self.has_last_modified_date():
            if LAST_MODIFIED_BYTES_LENGTH + index > n:
                raise ValueError("index out of range 4")
            self.last_modified = int.from_bytes(
                b[index : index + LAST_MODIFIED_BYTES_LENGTH], "big"
            )
            index += LAST_MODIFIED_BYTES_LENGTH
        if index < n and self.has_ttl():
            if TTL_BYTES_LENGTH + index > n:
                raise ValueError("index out of range 5")
            self.ttl = TTL.from_bytes(b[index : index + TTL_BYTES_LENGTH])
            index += TTL_BYTES_LENGTH
        if index < n and self.has_pairs():
            if 2 + index > n:
                raise ValueError("index out of range 6")
            pairs_size = bytes_to_u16(b[index : index + 2])
            index += 2
            if pairs_size + index > n:
                raise ValueError("index out of range 7")
            self.pairs = bytes(b[index : index + pairs_size])
            index += pairs_size

    def read_bytes(self, b: bytes, offset: int, size: int, version: int) -> None:
        """Hydrate from a full record blob; verifies size and CRC
        (ref needle_read_write.go:168-195)."""
        self.parse_header(b)
        if self.size != size:
            raise NotFoundError(
                f"entry not found: offset {offset} found id {self.id} "
                f"size {self.size}, expected size {size}"
            )
        mv = memoryview(b)  # body fields slice the blob zero-copy
        if version == VERSION1:
            self.data = mv[NEEDLE_HEADER_SIZE : NEEDLE_HEADER_SIZE + size]
        elif version in (VERSION2, VERSION3):
            self._read_data_v2(mv[NEEDLE_HEADER_SIZE : NEEDLE_HEADER_SIZE + self.size])
        else:
            raise ValueError(f"unsupported version {version}")
        if size > 0:
            stored = bytes_to_u32(
                b[NEEDLE_HEADER_SIZE + size : NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE]
            )
            computed = masked_crc(self.data)
            if stored != computed:
                raise CrcError("CRC error! Data On Disk Corrupted")
            self.checksum = computed
        if version == VERSION3:
            ts = NEEDLE_HEADER_SIZE + size + NEEDLE_CHECKSUM_SIZE
            self.append_at_ns = bytes_to_u64(b[ts : ts + TIMESTAMP_SIZE])

    def read_needle_body_bytes(self, body: bytes, version: int) -> None:
        """Hydrate from body bytes after the header was parsed separately
        (ref needle_read_write.go:323-344). Does NOT verify CRC; recomputes it."""
        if not body:
            return
        if version == VERSION1:
            self.data = body[: self.size]
            self.checksum = masked_crc(self.data)
        elif version in (VERSION2, VERSION3):
            self._read_data_v2(body[: self.size])
            self.checksum = masked_crc(self.data)
            if version == VERSION3:
                ts = self.size + NEEDLE_CHECKSUM_SIZE
                self.append_at_ns = bytes_to_u64(body[ts : ts + TIMESTAMP_SIZE])
        else:
            raise ValueError(f"unsupported version {version}")


def read_needle_blob(backend_file, offset: int, size: int, version: int) -> bytes:
    return backend_file.read_at(get_actual_size(size, version), offset)


def read_needle_data(backend_file, offset: int, size: int, version: int) -> Needle:
    n = Needle()
    blob = read_needle_blob(backend_file, offset, size, version)
    n.read_bytes(blob, offset, size, version)
    return n


def read_needle_header(backend_file, version: int, offset: int) -> tuple[Needle, int]:
    """Returns (needle_with_header, body_length)."""
    b = backend_file.read_at(NEEDLE_HEADER_SIZE, offset)
    if len(b) < NEEDLE_HEADER_SIZE:
        raise EOFError("short read at needle header")
    n = Needle()
    n.parse_header(b)
    return n, needle_body_length(n.size, version)
