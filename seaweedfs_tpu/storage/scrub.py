"""Background scrub: continuous verification of data at rest.

The read path only notices corruption when a client happens to ask for the
damaged needle; the paper's warm-storage posture (Haystack + f4) needs
latent damage found and repaired BEFORE a second failure makes it
unrecoverable. This module is the detection half of that loop:

- `scrub_volume` walks a volume's live index entries, cross-checks each
  entry's extent against the .dat, and re-reads every record through the
  CRC-verifying needle parser — bit rot, truncation and index/extent skew
  all surface as typed corruption findings;
- `scrub_ec_volume` re-derives parity from the data shards with the same
  RS codec that encoded them (TPU/native when configured — recompute-and-
  compare runs at encode throughput) and compares against the stored
  parity shards, identifying WHICH shard is damaged under the
  single-corruption assumption;
- `Scrubber` drives both over a whole Store with a byte/s token bucket
  (`SEAWEEDFS_TPU_SCRUB_MBPS`) so verification traffic is rate-shaped
  under serving load, and a persisted per-volume resume cursor
  (`<base>.scrub`) so a restarted server continues where it left off.

Quarantine policy: scrub never deletes. A corrupt volume goes read-only
with `scrub_corrupt` raised in its heartbeat message; a corrupt EC shard
is unmounted and renamed to `.ecNN.bad` (evidence intact) so the master's
repair scheduler sees it as missing and rebuilds it through the batched
fast path.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Optional

from ..types import TOMBSTONE_FILE_SIZE, to_actual_offset
from ..util.metrics import SCRUB_BYTES, SCRUB_CORRUPTIONS, SCRUB_PASSES
from .maintenance import TokenBucket, plane_bucket  # noqa: F401 — TokenBucket
# stays importable from here (its original home) for existing callers; the
# class itself moved to maintenance.py where it became the building block
# of the SHARED maintenance budget (scrub + vacuum + repair under one cap)
from .needle import get_actual_size, read_needle_data

# parity verification granularity: bytes per shard per round
EC_SCRUB_CHUNK = 1 << 20


# ---------------------------------------------------------------- cursor --


def _cursor_path(base: str) -> str:
    return base + ".scrub"


def load_cursor(base: str) -> dict:
    try:
        with open(_cursor_path(base)) as f:
            d = json.load(f)
            if isinstance(d, dict):
                return d
    except (OSError, ValueError):
        pass
    return {"resume_key": 0, "passes": 0}


def save_cursor(base: str, cursor: dict) -> None:
    tmp = _cursor_path(base) + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(cursor, f)
        os.replace(tmp, _cursor_path(base))
    except OSError:
        pass  # cursor is an optimization; losing it restarts the pass


# ------------------------------------------------------------ volume scrub --


def scrub_volume(
    v,
    bucket: Optional[TokenBucket] = None,
    resume: bool = True,
    max_entries: Optional[int] = None,
    quarantine: bool = True,
    cursor_every: int = 512,
) -> dict:
    """Verify a volume's live records against their index entries.

    Walks the live (non-tombstoned) index snapshot in key order from the
    persisted resume cursor, and for each entry: cross-checks that the
    record's extent fits the .dat, then re-reads the record through the
    CRC-verifying parser and confirms the stored id matches the index key.
    Rate-shaped by `bucket`; timesliced by `max_entries` (the cursor
    persists, the next call continues). Returns a report dict:
    {volume_id, scanned, bytes, completed, corruptions: [(key, kind,
    detail)]}. With `quarantine`, any finding marks the volume read-only
    (never deletes — see module docstring)."""
    base = v.file_name()
    cursor = load_cursor(base) if resume else {"resume_key": 0, "passes": 0}
    resume_key = int(cursor.get("resume_key", 0))
    report = {
        "volume_id": v.id,
        "scanned": 0,
        "bytes": 0,
        "completed": True,
        "corruptions": [],
    }
    try:
        with v._lock:
            keys, offsets, sizes = v.nm.snapshot()
    except Exception:
        # map kinds without a snapshot (exotic/remote): nothing to verify
        report["skipped"] = "no index snapshot"
        return report
    dat_size = v.data_file_size()
    version = v.version
    since_cursor = 0
    for i in range(len(keys)):
        key = int(keys[i])
        if resume_key and key <= resume_key:
            continue
        if max_entries is not None and report["scanned"] >= max_entries:
            report["completed"] = False
            break
        offset_units, size = int(offsets[i]), int(sizes[i])
        if offset_units == 0 or size == TOMBSTONE_FILE_SIZE:
            continue
        record_bytes = get_actual_size(size, version)
        offset = to_actual_offset(offset_units)
        if bucket is not None:
            bucket.consume(record_bytes)
        report["scanned"] += 1
        since_cursor += 1
        kind = None
        if offset + record_bytes > dat_size:
            kind = "idx_extent"
            detail = f"record end {offset + record_bytes} past dat {dat_size}"
        else:
            try:
                with v._lock:
                    n = read_needle_data(v.data_backend, offset, size, version)
                if n.id != key:
                    kind, detail = "needle_id", f"stored id {n.id:#x}"
                else:
                    report["bytes"] += record_bytes
            except Exception as e:
                kind, detail = "needle_crc", str(e)
        if kind is not None:
            report["corruptions"].append((key, kind, detail))
            SCRUB_CORRUPTIONS.inc(kind=kind)
        resume_key = key
        if since_cursor >= cursor_every:
            save_cursor(base, {**cursor, "resume_key": resume_key})
            since_cursor = 0
    SCRUB_BYTES.inc(report["bytes"], kind="dat")
    if report["completed"]:
        save_cursor(
            base, {"resume_key": 0, "passes": int(cursor.get("passes", 0)) + 1}
        )
        SCRUB_PASSES.inc(plane="volume")
    else:
        save_cursor(base, {**cursor, "resume_key": resume_key})
    if quarantine and report["corruptions"]:
        first = report["corruptions"][0]
        v.quarantine(
            f"scrub: {len(report['corruptions'])} corrupt record(s), "
            f"first key {first[0]:#x} ({first[1]})"
        )
    return report


# ---------------------------------------------------------------- EC scrub --


def _read_chunk(path: str, offset: int, size: int):
    import numpy as np

    with open(path, "rb") as f:
        b = os.pread(f.fileno(), size, offset)
    if len(b) < size:
        b = b + b"\x00" * (size - len(b))
    return np.frombuffer(b, dtype=np.uint8)


def _identify_corrupt_data_shard(codec, data_rows, parity_rows, present_parity):
    """Single-corruption identification when EVERY stored parity row
    disagrees with the recomputed parity: try each data shard d as the
    culprit — reconstruct d from the other shards, and if re-encoding with
    the reconstruction makes all stored parity verify, d was the damaged
    shard. Returns the shard id or None (multi-corruption: unidentified)."""
    import numpy as np

    k = codec.data_shards
    for d in range(k):
        shards = [None] * codec.total_shards
        for i in range(k):
            if i != d:
                shards[i] = data_rows[i]
        for j, pid in enumerate(present_parity):
            shards[k + pid] = parity_rows[j]
        try:
            rows = codec.reconstruct_rows(shards, [d])
        except Exception:
            continue
        if rows[0] is None:
            continue
        candidate = list(data_rows)
        candidate[d] = np.asarray(rows[0], dtype=np.uint8)
        recalced = codec.encode(np.stack(candidate))
        if all(
            np.array_equal(recalced[pid], parity_rows[j])
            for j, pid in enumerate(present_parity)
        ):
            return d
    return None


def scrub_ec_volume(
    base: str,
    codec,
    bucket: Optional[TokenBucket] = None,
    chunk: int = EC_SCRUB_CHUNK,
) -> dict:
    """Verify an EC volume's parity by recomputation: for each aligned
    chunk, re-encode the k data-shard rows through `codec` (the same
    kernels the encode pipeline uses) and compare against every locally
    present parity shard. Needs all k data shards on this server — a
    spread volume reports {"skipped": ...} instead of guessing. Returns
    {base, shard_size, bytes, corrupt_shards: [ids], unidentified: bool};
    corrupt shard ids are established per the single-corruption heuristic
    (a lone disagreeing parity shard is itself damaged; a unanimous
    disagreement is traced back to the data shard whose reconstruction
    restores consistency)."""
    import numpy as np

    from .erasure_coding import to_ext

    k, m = codec.data_shards, codec.parity_shards
    present = [
        i for i in range(codec.total_shards) if os.path.exists(base + to_ext(i))
    ]
    report = {
        "base": base,
        "bytes": 0,
        "corrupt_shards": [],
        "unidentified": False,
    }
    if any(i not in present for i in range(k)):
        report["skipped"] = (
            f"data shards {[i for i in range(k) if i not in present]} not "
            "local; parity cannot be recomputed here"
        )
        return report
    present_parity = [i - k for i in present if i >= k]
    if not present_parity:
        report["skipped"] = "no parity shards local"
        return report
    sizes = {i: os.path.getsize(base + to_ext(i)) for i in present}
    shard_size = max(set(sizes.values()), key=lambda s: list(sizes.values()).count(s))
    odd = sorted(i for i, s in sizes.items() if s != shard_size)
    corrupt: set[int] = set(odd)
    for i in odd:
        SCRUB_CORRUPTIONS.inc(kind="ec_shard_size")
    report["shard_size"] = shard_size
    for off in range(0, shard_size, chunk):
        width = min(chunk, shard_size - off)
        if bucket is not None:
            bucket.consume(width * (k + len(present_parity)))
        data_rows = [
            _read_chunk(base + to_ext(i), off, width) for i in range(k)
        ]
        parity_rows = [
            _read_chunk(base + to_ext(k + p), off, width)
            for p in present_parity
        ]
        calc = codec.encode(np.stack(data_rows))
        bad = [
            p
            for j, p in enumerate(present_parity)
            if not np.array_equal(calc[p], parity_rows[j])
        ]
        report["bytes"] += width * (k + len(present_parity))
        if not bad:
            continue
        if len(bad) < len(present_parity):
            # some parity rows still verify against the recomputation, so
            # the data shards are intact: the disagreeing parity shards
            # themselves are damaged
            for p in bad:
                if k + p not in corrupt:
                    corrupt.add(k + p)
                    SCRUB_CORRUPTIONS.inc(kind="ec_parity")
        else:
            d = _identify_corrupt_data_shard(
                codec, data_rows, parity_rows, present_parity
            )
            if d is None:
                report["unidentified"] = True
                SCRUB_CORRUPTIONS.inc(kind="ec_unidentified")
            elif d not in corrupt:
                corrupt.add(d)
                SCRUB_CORRUPTIONS.inc(kind="ec_data")
    SCRUB_BYTES.inc(report["bytes"], kind="ec")
    SCRUB_PASSES.inc(plane="ec")
    report["corrupt_shards"] = sorted(corrupt)
    return report


# ---------------------------------------------------------------- driver --


class Scrubber:
    """Store-wide scrub driver: one pass = every volume (resumable via the
    per-volume cursor) + every EC volume with locally verifiable parity.
    Applies the quarantine policy and queues the heartbeat deltas that
    carry findings to the master's repair scheduler."""

    def __init__(
        self,
        store,
        rate_mbps: float = 0.0,
        codec_for: Optional[Callable[[int, int], object]] = None,
    ):
        self.store = store
        # an explicit scrub rate wins; otherwise the shared maintenance
        # budget (SEAWEEDFS_TPU_MAINT_MBPS) shapes scrub I/O jointly with
        # vacuum and repair so the planes' SUM stays under one cap
        self.bucket = plane_bucket(
            "scrub",
            TokenBucket(rate_mbps * 1e6)
            if rate_mbps and rate_mbps > 0
            else None,
        )
        self.codec_for = codec_for

    def run_pass(
        self,
        volume_id: Optional[int] = None,
        include_ec: bool = True,
        max_entries_per_volume: Optional[int] = None,
    ) -> dict:
        reports = {"volumes": [], "ec_volumes": [], "quarantined": []}
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                if volume_id and vid != volume_id:
                    continue
                if v.has_remote_file or v.is_compacting:
                    continue  # tiered / mid-vacuum: nothing verifiable here
                old_msg = self.store._volume_message(v)
                r = scrub_volume(
                    v,
                    self.bucket,
                    max_entries=max_entries_per_volume,
                    quarantine=False,
                )
                if r["corruptions"]:
                    # a vacuum commit may have swapped the Volume object
                    # (new .dat, new offsets) mid-pass, making OUR snapshot
                    # offsets stale — findings must be confirmed against
                    # the CURRENT object before they quarantine anything
                    cur = loc.volumes.get(vid)
                    if cur is not v and cur is not None:
                        r = scrub_volume(
                            cur, self.bucket, resume=False, quarantine=False
                        )
                        v, old_msg = cur, self.store._volume_message(cur)
                reports["volumes"].append(r)
                if r["corruptions"]:
                    first = r["corruptions"][0]
                    v.quarantine(
                        f"scrub: {len(r['corruptions'])} corrupt record(s), "
                        f"first key {first[0]:#x} ({first[1]})"
                    )
                    # push the quarantine to the master on the next pulse
                    self.store.note_volume_changed(
                        old_msg, self.store._volume_message(v)
                    )
                    reports["quarantined"].append({"volume_id": vid})
            if not include_ec:
                continue
            for vid, ev in list(loc.ec_volumes.items()):
                if volume_id and vid != volume_id:
                    continue
                codec = self._codec(ev)
                if codec is None:
                    continue
                r = scrub_ec_volume(ev.file_name(), codec, self.bucket)
                r["volume_id"] = vid
                reports["ec_volumes"].append(r)
                for shard_id in r["corrupt_shards"]:
                    if self.quarantine_ec_shard(loc, ev, shard_id):
                        reports["quarantined"].append(
                            {"volume_id": vid, "shard_id": shard_id}
                        )
        return reports

    def _codec(self, ev):
        if self.codec_for is not None:
            return self.codec_for(ev.data_shards, ev.parity_shards)
        try:
            from ..tpu.coder import get_codec

            return get_codec("cpu", ev.data_shards, ev.parity_shards)
        except Exception:
            return None

    def quarantine_ec_shard(self, loc, ev, shard_id: int) -> bool:
        """Corrupt shard: unmount it and move the file aside to `.bad`
        (evidence intact, never deleted). The heartbeat delta reports the
        shard gone, which is exactly the state the master's repair
        scheduler knows how to fix — rebuild from survivors through the
        batched fast path."""
        from ..util.log import warning

        from .erasure_coding import to_ext
        from .erasure_coding.ec_volume import ShardBits

        vid, collection = ev.volume_id, ev.collection
        base = ev.file_name()
        path = base + to_ext(shard_id)
        if not os.path.exists(path):
            return False
        loc.unload_ec_shard(vid, shard_id)
        try:
            os.replace(path, path + ".bad")
        except OSError:
            return False
        self.store.note_ec_shards_changed(
            vid, collection, ShardBits(), ShardBits().add(shard_id)
        )
        warning(
            "ec volume %d: shard %d failed parity verification, "
            "quarantined to %s.bad", vid, shard_id, path,
        )
        return True
