"""Backend storage-file abstraction.

Mirrors the reference's BackendStorageFile interface (read_at/write_at/
truncate/sync/size; ref: weed/storage/backend/backend.go:15-23) with a
positional-IO disk implementation (os.pread/os.pwrite, safe for concurrent
readers) and an in-memory implementation for tests and tiering scratch.
"""

from __future__ import annotations

import os
import threading
from typing import Protocol

from ..util import faults


class BackendStorageFile(Protocol):
    def read_at(self, size: int, offset: int) -> bytes: ...
    def write_at(self, data: bytes, offset: int) -> int: ...
    def truncate(self, size: int) -> None: ...
    def sync(self) -> None: ...
    def size(self) -> int: ...
    def close(self) -> None: ...
    @property
    def name(self) -> str: ...


class DiskFile:
    """Positional-IO file; append position is size() (no shared cursor).

    The size is tracked in-process (updated by write_at/truncate) instead
    of fstat-ing per call: the serving write path asks for it ~3x per
    request and the fstat syscalls were measurable at QPS rates. This
    object is the file's single writer within the process; anything that
    replaces the file on disk (vacuum commit, copy) reopens the backend."""

    def __init__(self, path: str, create: bool = True, read_only: bool = False):
        self._path = path
        self._read_only = read_only
        if read_only:
            flags = os.O_RDONLY
        else:
            flags = os.O_RDWR | (os.O_CREAT if create else 0)
        self._fd = os.open(path, flags, 0o644)
        self._closed = False
        self._size = os.fstat(self._fd).st_size

    @property
    def name(self) -> str:
        return self._path

    def read_at(self, size: int, offset: int) -> bytes:
        flip = None
        if faults._PLAN is not None:
            flip = faults.sync_fault(
                faults._PLAN, "read_at", self._path, corruptable=True
            )
        chunks = []
        remaining, pos = size, offset
        while remaining > 0:
            b = os.pread(self._fd, remaining, pos)
            if not b:
                break
            chunks.append(b)
            remaining -= len(b)
            pos += len(b)
        out = b"".join(chunks)
        if flip is not None and flip.kind == "bitflip":
            # transient read-side corruption (bad cable / lying controller):
            # the bytes on disk stay intact, this read sees flipped bits
            out = faults.apply_bitflip(flip, out, offset)
        return out

    def write_at(self, data: bytes, offset: int) -> int:
        if faults._PLAN is not None:
            data = self._faulted_write(faults._PLAN, data, offset)
        view = memoryview(data)
        pos = offset
        while view:
            n = os.pwrite(self._fd, view, pos)
            view = view[n:]
            pos += n
        if pos > self._size:
            self._size = pos
        return pos - offset

    def _faulted_write(self, plan, data: bytes, offset: int) -> bytes:
        """Consult the fault plan for this write. Latency/EIO are applied
        by sync_fault; torn/crash writes are applied here: the kept prefix
        is persisted and the fault raised, leaving a short record on disk
        exactly as an interrupted pwrite chain would."""
        ev = faults.sync_fault(
            plan, "write_at", self._path, allow_partial=True, corruptable=True
        )
        if ev is None:
            return data
        if ev.kind == "bitflip":
            # silent write-path corruption: the flipped bytes are what
            # lands on disk (and what any verify-after-write would see) —
            # the canonical seed for scrub-detection tests
            return faults.apply_bitflip(ev, data, offset)
        if ev.kind in ("torn", "crash"):
            rule = ev.rule
            if rule.at_offset is not None:
                keep = max(0, min(len(data), rule.at_offset - offset))
            elif rule.keep is not None:
                keep = min(rule.keep, len(data))
            else:
                keep = ev.rng.randrange(len(data) + 1)
            view = memoryview(data)[:keep]
            pos = offset
            while view:
                n = os.pwrite(self._fd, view, pos)
                view = view[n:]
                pos += n
            if pos > self._size:
                self._size = pos
            if ev.kind == "crash":
                plan.mark_dead()
                raise faults.SimulatedCrash(
                    f"crash after {keep}/{len(data)} bytes at "
                    f"{self._path}:{offset}"
                )
            raise faults.injected_eio(self._path)
        return data

    def append(self, data: bytes) -> int:
        """Append at current end; returns the offset written at."""
        end = self.size()
        self.write_at(data, end)
        return end

    def truncate(self, size: int) -> None:
        if faults._PLAN is not None:
            faults.sync_fault(faults._PLAN, "truncate", self._path)
        os.ftruncate(self._fd, size)
        self._size = size

    def sync(self) -> None:
        if faults._PLAN is not None:
            faults.sync_fault(faults._PLAN, "sync", self._path)
        os.fsync(self._fd)

    def size(self) -> int:
        if self._read_only:
            # read-only opens (cli fix/verify, vacuum sources) may watch a
            # file another writer is appending to; the in-process cache
            # below is valid only under the single-writer invariant
            return os.fstat(self._fd).st_size
        return self._size

    def close(self) -> None:
        if not self._closed:
            os.close(self._fd)
            self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MemoryFile:
    """In-memory BackendStorageFile for tests."""

    def __init__(self, name: str = "<memory>", data: bytes = b""):
        self._name = name
        self._buf = bytearray(data)
        self._lock = threading.Lock()

    @property
    def name(self) -> str:
        return self._name

    def read_at(self, size: int, offset: int) -> bytes:
        with self._lock:
            return bytes(self._buf[offset : offset + size])

    def write_at(self, data: bytes, offset: int) -> int:
        with self._lock:
            end = offset + len(data)
            if end > len(self._buf):
                self._buf.extend(b"\x00" * (end - len(self._buf)))
            self._buf[offset:end] = data
            return len(data)

    def append(self, data: bytes) -> int:
        with self._lock:
            end = len(self._buf)
            self._buf.extend(data)
            return end

    def truncate(self, size: int) -> None:
        with self._lock:
            del self._buf[size:]

    def sync(self) -> None:
        pass

    def size(self) -> int:
        return len(self._buf)

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
