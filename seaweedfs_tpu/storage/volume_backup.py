"""Incremental volume backup/tail by AppendAtNs.

Every v3 needle carries its append timestamp; since the .dat is append-only
the timestamps are monotonic, so a binary search over record boundaries
finds the resume offset for an incremental pull
(ref: weed/storage/volume_backup.go:65-170 BinarySearchForAppendAtNs).
"""

from __future__ import annotations

from typing import Iterator

from ..types import NEEDLE_HEADER_SIZE, VERSION3
from .needle import read_needle_header
from .volume import Volume


def _record_bounds(v: Volume) -> list[tuple[int, int]]:
    """(offset, append_at_ns) for every record, in file order."""
    bounds = []

    def visit(n, offset, body):
        bounds.append((offset, n.append_at_ns))

    v.scan(visit, read_body=True)
    return bounds


def binary_search_append_at_ns(v: Volume, since_ns: int) -> int:
    """Smallest file offset whose record has append_at_ns > since_ns;
    volume end when everything is older."""
    if v.version != VERSION3:
        # no timestamps before v3: restart from the superblock
        return v.super_block.block_size() if since_ns == 0 else v.data_file_size()
    bounds = _record_bounds(v)
    lo, hi = 0, len(bounds)
    while lo < hi:
        mid = (lo + hi) // 2
        if bounds[mid][1] <= since_ns:
            lo = mid + 1
        else:
            hi = mid
    if lo == len(bounds):
        return v.data_file_size()
    return bounds[lo][0]


def incremental_changes(
    v: Volume, since_ns: int, chunk: int = 1 << 20
) -> Iterator[bytes]:
    """Raw .dat bytes of all records appended after since_ns."""
    offset = binary_search_append_at_ns(v, since_ns)
    end = v.data_file_size()
    while offset < end:
        data = v.data_backend.read_at(min(chunk, end - offset), offset)
        if not data:
            return
        yield data
        offset += len(data)


def apply_incremental(v: Volume, data: bytes) -> int:
    """Append pulled records and replay them into the needle map; returns the
    number of records applied (ref volume_backup.go IncrementalBackup's
    write-back path)."""
    from ..types import TOMBSTONE_FILE_SIZE, to_offset_units
    from .needle import needle_body_length

    start = v.data_backend.size()
    v.data_backend.write_at(data, start)
    applied = 0
    offset = start
    end = v.data_backend.size()
    while offset + NEEDLE_HEADER_SIZE <= end:
        n, body_len = read_needle_header(v.data_backend, v.version, offset)
        body = v.data_backend.read_at(body_len, offset + NEEDLE_HEADER_SIZE)
        n.read_needle_body_bytes(body, v.version)
        if n.size > 0:
            v.nm.put(n.id, to_offset_units(offset), n.size)
        else:
            v.nm.delete(n.id, to_offset_units(offset))
        v.last_append_at_ns = n.append_at_ns
        offset += NEEDLE_HEADER_SIZE + body_len
        applied += 1
    return applied
