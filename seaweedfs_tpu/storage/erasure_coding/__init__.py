"""Reed-Solomon erasure coding — RS(10,4) over a two-level block layout.

Geometry and file formats match the reference (ref: weed/storage/
erasure_coding/ec_encoder.go:17-23): 10 data + 4 parity shards, 1GB large
blocks striped row-major until <10GB remains, then 1MB small blocks; shard
files .ec00-.ec13, sorted index .ecx, deletion journal .ecj.

The GF(2^8) arithmetic (galois.py) reproduces klauspost/reedsolomon's
Vandermonde-derived systematic matrix so shards are byte-identical to ones
produced by the reference. The compute path is pluggable: numpy on CPU
(coder_cpu.py) or the JAX/Pallas TPU kernel (ops/rs_kernel.py) behind the
same RSCodec interface.
"""

DATA_SHARDS_COUNT = 10
PARITY_SHARDS_COUNT = 4
TOTAL_SHARDS_COUNT = DATA_SHARDS_COUNT + PARITY_SHARDS_COUNT
EC_LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1GB
EC_SMALL_BLOCK_SIZE = 1024 * 1024  # 1MB


def to_ext(ec_index: int) -> str:
    return f".ec{ec_index:02d}"


from .locate import Interval, locate_data  # noqa: E402
from .coder_cpu import CpuRSCodec  # noqa: E402
from .encoder import (  # noqa: E402
    write_ec_files,
    write_ec_files_multi,
    rebuild_ec_files,
    rebuild_ec_files_multi,
    write_sorted_file_from_idx,
    write_dat_file,
    write_idx_file_from_ec_index,
    find_dat_file_size,
)
from .ec_volume import EcVolume, EcVolumeShard, search_needle_from_sorted_index  # noqa: E402

__all__ = [
    "DATA_SHARDS_COUNT",
    "PARITY_SHARDS_COUNT",
    "TOTAL_SHARDS_COUNT",
    "EC_LARGE_BLOCK_SIZE",
    "EC_SMALL_BLOCK_SIZE",
    "to_ext",
    "Interval",
    "locate_data",
    "CpuRSCodec",
    "write_ec_files",
    "write_ec_files_multi",
    "rebuild_ec_files",
    "rebuild_ec_files_multi",
    "write_sorted_file_from_idx",
    "write_dat_file",
    "write_idx_file_from_ec_index",
    "find_dat_file_size",
    "EcVolume",
    "EcVolumeShard",
    "search_needle_from_sorted_index",
]
