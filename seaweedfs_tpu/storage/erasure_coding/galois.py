"""GF(2^8) arithmetic and Reed-Solomon matrix construction.

Field: polynomial x^8 + x^4 + x^3 + x^2 + 1 (0x11D), generator 2 — the same
field klauspost/reedsolomon (and Backblaze's JavaReedSolomon) uses, so the
systematic encode matrix built here is element-identical to the one the
reference's `reedsolomon.New(10, 4)` produces and the parity shards are
byte-identical (ref: ec_encoder.go:198).

Construction: vm[r][c] = r^c in GF (a Vandermonde matrix), then
matrix = vm * inverse(vm[:k]) so the top k rows are the identity and the
remaining m rows generate parity.
"""

from __future__ import annotations

import numpy as np

FIELD_POLY = 0x11D
GENERATOR = 2

# --- exp/log tables ---
EXP_TABLE = np.zeros(512, dtype=np.uint8)  # doubled to skip the mod in hot paths
LOG_TABLE = np.zeros(256, dtype=np.int32)


def _build_tables() -> None:
    x = 1
    for i in range(255):
        EXP_TABLE[i] = x
        LOG_TABLE[x] = i
        x <<= 1
        if x & 0x100:
            x ^= FIELD_POLY
    for i in range(255, 512):
        EXP_TABLE[i] = EXP_TABLE[i - 255]


_build_tables()

# Full 256x256 multiplication table: MUL_TABLE[a][b] = a*b in GF(2^8).
# 64KB; the row MUL_TABLE[c] is the byte-level lookup used by the vectorized
# numpy encoder and by table-based kernels.
_a = np.arange(256, dtype=np.int32)
_log_sum = LOG_TABLE[:, None] + LOG_TABLE[None, :]
MUL_TABLE = EXP_TABLE[_log_sum % 255].astype(np.uint8)
MUL_TABLE[0, :] = 0
MUL_TABLE[:, 0] = 0
del _a, _log_sum


def gf_mul(a: int, b: int) -> int:
    return int(MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("division by zero in GF(2^8)")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("zero has no inverse in GF(2^8)")
    return int(EXP_TABLE[(255 - LOG_TABLE[a]) % 255])


def gf_exp(a: int, n: int) -> int:
    """a^n in GF(2^8) (ref: klauspost galois.go galExp semantics)."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] * n) % 255])


def gf_mul_row(c: int, data: np.ndarray) -> np.ndarray:
    """Multiply every byte of `data` by constant c (table gather)."""
    return MUL_TABLE[c][data]


# --- matrix algebra over GF(2^8) ---
def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF matrix product of small uint8 matrices."""
    rows, inner = a.shape
    inner2, cols = b.shape
    assert inner == inner2
    out = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        acc = np.zeros(cols, dtype=np.uint8)
        for k in range(inner):
            acc ^= MUL_TABLE[a[r, k]][b[k]]
        out[r] = acc
    return out


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8)."""
    n = m.shape[0]
    assert m.shape == (n, n)
    aug = np.zeros((n, 2 * n), dtype=np.uint8)
    aug[:, :n] = m
    aug[:, n:] = np.eye(n, dtype=np.uint8)
    for col in range(n):
        # find pivot
        pivot = -1
        for r in range(col, n):
            if aug[r, col] != 0:
                pivot = r
                break
        if pivot < 0:
            raise ValueError("singular matrix in GF(2^8) inversion")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        # scale pivot row to 1
        inv = gf_inv(int(aug[col, col]))
        aug[col] = MUL_TABLE[inv][aug[col]]
        # eliminate other rows
        for r in range(n):
            if r != col and aug[r, col] != 0:
                aug[r] ^= MUL_TABLE[int(aug[r, col])][aug[col]]
    return aug[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """vm[r][c] = r^c in GF (ref: klauspost matrix.go vandermonde)."""
    vm = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            vm[r, c] = gf_exp(r, c)
    return vm


def build_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Systematic encode matrix, identical to klauspost's buildMatrix:
    identity on top, parity generator rows below."""
    vm = vandermonde(total_shards, data_shards)
    top = vm[:data_shards]
    return mat_mul(vm, mat_inv(top))


def sub_matrix_for_survivors(
    full_matrix: np.ndarray, survivor_rows: list[int]
) -> np.ndarray:
    """Rows of the full (n x k) matrix for the given surviving shard ids."""
    return full_matrix[np.asarray(survivor_rows)]


def reconstruction_matrix(
    full_matrix: np.ndarray, survivor_rows: list[int]
) -> np.ndarray:
    """Inverse of the survivor submatrix: maps k survivor shards back to the
    k data shards. survivor_rows must have exactly k entries."""
    k = full_matrix.shape[1]
    if len(survivor_rows) != k:
        raise ValueError(f"need exactly {k} survivors, got {len(survivor_rows)}")
    return mat_inv(sub_matrix_for_survivors(full_matrix, survivor_rows))


def compose_decode_rows(
    full_matrix: np.ndarray, survivors: list[int], wanted: list[int]
) -> np.ndarray:
    """The (len(wanted) x k) matrix that maps k survivor shards DIRECTLY to
    the wanted shard ids — data rows come from the survivor inverse, parity
    rows are the parity generator composed with that inverse (exact GF
    algebra, so the output is byte-identical to reconstructing all data and
    re-encoding the parity)."""
    k = full_matrix.shape[1]
    dec = reconstruction_matrix(full_matrix, survivors)
    rows = np.empty((len(wanted), k), dtype=np.uint8)
    for r, i in enumerate(wanted):
        if i < k:
            rows[r] = dec[i]
        else:
            rows[r] = mat_mul(full_matrix[i : i + 1], dec)[0]
    return rows


class DecodeRowsCache:
    """Bounded LRU of composed decode matrices keyed by (geometry, survivor
    set, wanted rows) — shared by rebuild_ec_files and the degraded-read
    path so a steady repair workload pays the Gauss-Jordan inversion once
    per missing-shard pattern, not once per chunk/interval."""

    def __init__(self, maxsize: int = 256):
        import threading
        from collections import OrderedDict

        self.maxsize = maxsize
        self._entries: "OrderedDict[tuple, np.ndarray]" = OrderedDict()
        self._lock = threading.Lock()

    def rows_for(
        self, full_matrix: np.ndarray, survivors: list[int], wanted: list[int]
    ) -> np.ndarray:
        key = (
            full_matrix.shape[0],
            full_matrix.shape[1],
            tuple(survivors),
            tuple(wanted),
        )
        with self._lock:
            rows = self._entries.get(key)
            if rows is not None:
                self._entries.move_to_end(key)
        try:
            from ...util.metrics import EC_DECODE_MATRIX_CACHE

            EC_DECODE_MATRIX_CACHE.inc(
                outcome="hit" if rows is not None else "miss"
            )
        except ImportError:  # metrics must never break the math path
            pass
        if rows is not None:
            return rows
        rows = compose_decode_rows(full_matrix, survivors, wanted)
        with self._lock:
            self._entries[key] = rows
            self._entries.move_to_end(key)
            while len(self._entries) > self.maxsize:
                self._entries.popitem(last=False)
        return rows

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# process-wide instance (all geometries share it; keys carry the geometry)
DECODE_ROWS_CACHE = DecodeRowsCache()
