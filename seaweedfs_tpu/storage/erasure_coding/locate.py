"""Needle -> shard interval math for the two-level EC block layout.

Layout (ref: weed/storage/erasure_coding/ec_locate.go): the .dat is striped
row-major over 10 data shards in 1GB "large" blocks while >=1 full large row
remains, then in 1MB "small" blocks. A needle spanning block boundaries maps
to multiple intervals; each interval resolves to (shard id, offset inside the
shard file) where the shard file holds its large blocks first, then its small
blocks (ec_locate.go:73-83).
"""

from __future__ import annotations

from dataclasses import dataclass

from . import DATA_SHARDS_COUNT


@dataclass(frozen=True)
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int
    data_shards: int = DATA_SHARDS_COUNT  # row width (k of the RS geometry)

    def to_shard_id_and_offset(
        self, large_block_size: int, small_block_size: int
    ) -> tuple[int, int]:
        """Ref ec_locate.go:73-83."""
        ec_file_offset = self.inner_block_offset
        row_index = self.block_index // self.data_shards
        if self.is_large_block:
            ec_file_offset += row_index * large_block_size
        else:
            ec_file_offset += (
                self.large_block_rows_count * large_block_size
                + row_index * small_block_size
            )
        shard_id = self.block_index % self.data_shards
        return shard_id, ec_file_offset


def _locate_offset_within_blocks(block_length: int, offset: int) -> tuple[int, int]:
    return offset // block_length, offset % block_length


def _locate_offset(
    large_block_length: int,
    small_block_length: int,
    dat_size: int,
    offset: int,
    data_shards: int = DATA_SHARDS_COUNT,
) -> tuple[int, bool, int]:
    large_row_size = large_block_length * data_shards
    n_large_block_rows = dat_size // large_row_size
    if offset < n_large_block_rows * large_row_size:
        block_index, inner = _locate_offset_within_blocks(large_block_length, offset)
        return block_index, True, inner
    offset -= n_large_block_rows * large_row_size
    block_index, inner = _locate_offset_within_blocks(small_block_length, offset)
    return block_index, False, inner


def locate_data(
    large_block_length: int,
    small_block_length: int,
    dat_size: int,
    offset: int,
    size: int,
    data_shards: int = DATA_SHARDS_COUNT,
) -> list[Interval]:
    """Ref LocateData (ec_locate.go:11-48); data_shards parametrizes the
    row width for alternate RS geometries (6.3 / 12.4).

    Faithful to a latent reference BUG: three row-count derivations
    disagree in a narrow window. The encoder's large-row loop uses
    strictly-greater (ec_encoder.go:214), _locate_offset's layout
    boundary uses dat_size//(L*k) (ec_locate.go:52), and the
    large->small transition plus ToShardIdAndOffset use the
    shard-derived +k*S addend count (ec_locate.go:15,73-83). For
    dat_size in [n*L*k - k*S, n*L*k] — ~10MB per 10GB at real
    geometry — the reference's own reader mis-addresses shards ITS OWN
    encoder wrote. Reproduced identically here for wire parity;
    tests/test_property.py pins both the consistent domain and the
    broken window (test_ec_row_boundary_window_is_reference_faithful)."""
    block_index, is_large_block, inner_block_offset = _locate_offset(
        large_block_length, small_block_length, dat_size, offset, data_shards
    )
    # adding DataShardsCount*smallBlockLength ensures the large-row count can
    # be derived from a shard size (ec_locate.go:14-15)
    n_large_block_rows = (dat_size + data_shards * small_block_length) // (
        large_block_length * data_shards
    )

    intervals: list[Interval] = []
    while size > 0:
        block_remaining = (
            large_block_length - inner_block_offset
            if is_large_block
            else small_block_length - inner_block_offset
        )
        if size <= block_remaining:
            intervals.append(
                Interval(
                    block_index=block_index,
                    inner_block_offset=inner_block_offset,
                    size=size,
                    is_large_block=is_large_block,
                    large_block_rows_count=n_large_block_rows,
                    data_shards=data_shards,
                )
            )
            return intervals
        intervals.append(
            Interval(
                block_index=block_index,
                inner_block_offset=inner_block_offset,
                size=block_remaining,
                is_large_block=is_large_block,
                large_block_rows_count=n_large_block_rows,
                data_shards=data_shards,
            )
        )
        size -= block_remaining
        block_index += 1
        if is_large_block and block_index == n_large_block_rows * data_shards:
            is_large_block = False
            block_index = 0
        inner_block_offset = 0
    return intervals
