"""Native-SIMD RS codec: CpuRSCodec's interface over the C++ GF(2^8) kernel
(GFNI VGF2P8AFFINEQB where the CPU has it, PSHUFB nibble tables otherwise).

The production host-side codec (the numpy table path stays as the oracle);
decode matrices still come from the numpy galois module — only the bulk
byte-stream matmul runs natively.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from .coder_cpu import CpuRSCodec


class NativeRSCodec(CpuRSCodec):
    # ctypes releases the GIL for the duration of the native matmul, so the
    # file pipeline's worker pool parallelizes encode across cores — the
    # multi-core equivalent of klauspost/reedsolomon's WithAutoGoroutines
    # (the reference's ec_encoder.go:120-136 stays single-threaded)
    preferred_chunk = 4 * 1024 * 1024
    zero_copy_rows = True  # encode_rows takes per-row pointers (mmap views)

    def __init__(self, data_shards: int = 10, parity_shards: int = 4):
        super().__init__(data_shards, parity_shards)
        from ... import native

        if not native.available():
            raise RuntimeError("native gf256 library unavailable")
        self._native = native
        from ...util import available_cpus

        ncpu = available_cpus()
        self.prefers_pipeline = ncpu > 1
        self.pipeline_workers = max(2, min(8, ncpu))

    def _mat_apply(self, m: np.ndarray, data: np.ndarray) -> np.ndarray:
        return self._native.gf_matmul_native(m, data)

    def _apply_rows(self, m: np.ndarray, rows, out=None) -> np.ndarray:
        # decode-side analogue of encode_rows: the survivor chunks (read
        # buffers, mmap views) go to the kernel as row pointers and the
        # result lands in the caller's recycled `out` — reconstruct_rows
        # pays neither a k-row stack copy nor a fresh output allocation
        # per chunk
        return self._native.gf_matmul_rows_native(m, rows, out=out)

    def encode_rows(self, rows) -> np.ndarray:
        # per-row pointers straight into the kernel — mmap views encode
        # without ever being copied into a stacked buffer
        assert len(rows) == self.data_shards
        return self._native.gf_matmul_rows_native(self.parity_matrix, rows)
