"""Native-SIMD RS codec: CpuRSCodec's interface over the C++ PSHUFB kernel.

The production host-side codec (the numpy table path stays as the oracle);
decode matrices still come from the numpy galois module — only the bulk
byte-stream matmul runs natively.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .coder_cpu import CpuRSCodec


class NativeRSCodec(CpuRSCodec):
    def __init__(self, data_shards: int = 10, parity_shards: int = 4):
        super().__init__(data_shards, parity_shards)
        from ... import native

        if not native.available():
            raise RuntimeError("native gf256 library unavailable")
        self._native = native

    def _mat_apply(self, m: np.ndarray, data: np.ndarray) -> np.ndarray:
        return self._native.gf_matmul_native(m, data)
